"""Prometheus metrics for the BLS verifier pool.

Mirrors the reference's blsThreadPool metric family
(packages/beacon-node/src/metrics/metrics/lodestar.ts:440-510), feeding the
same dashboard shapes (dashboards/lodestar_bls_thread_pool.json).
"""
from __future__ import annotations

from prometheus_client import Counter, Gauge, Histogram, REGISTRY


class BlsPoolMetrics:
    _instance = None

    def __init__(self, registry=REGISTRY):
        ns = "lodestar_tpu_bls_pool"
        self.job_queue_length = Gauge(
            f"{ns}_queue_length", "Signature sets buffered awaiting a batch", registry=registry
        )
        self.jobs_started = Counter(
            f"{ns}_jobs_started_total", "Device verification jobs launched", registry=registry
        )
        self.sig_sets_total = Counter(
            f"{ns}_sig_sets_total", "Signature sets verified", registry=registry
        )
        self.batch_retries = Counter(
            f"{ns}_batch_retries_total",
            "Batches that failed and fell back to per-set verification",
            registry=registry,
        )
        self.invalid_sets = Counter(
            f"{ns}_invalid_sig_sets_total", "Individual sets that failed", registry=registry
        )
        self.job_wait_time = Histogram(
            f"{ns}_job_wait_time_seconds",
            "Time a set waits in the batching buffer",
            buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.2, 0.5, 1, 2),
            registry=registry,
        )
        self.job_run_time = Histogram(
            f"{ns}_job_run_time_seconds",
            "Device kernel wall time per job",
            buckets=(0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1, 2, 5),
            registry=registry,
        )
        self.encode_time = Histogram(
            f"{ns}_encode_time_seconds",
            "Host encode stage wall time per job (expand_message_xmd + "
            "field-draw reduction + limb packing; overlaps device "
            "execution of the previous job)",
            buckets=(0.001, 0.005, 0.02, 0.05, 0.1, 0.25, 0.5, 1),
            registry=registry,
        )
        # AOT compile-lifecycle observability (lodestar_tpu/aot): XLA
        # compile times and persistent-cache traffic seen by THIS
        # process, plus warm-manifest freshness at pool construction —
        # a cold first-verify is visible before it costs a slot.
        self.compile_time = Histogram(
            f"{ns}_xla_compile_seconds",
            "XLA compile wall time per program (persistent-cache misses)",
            buckets=(1, 5, 15, 60, 300, 900, 1800, 3600),
            registry=registry,
        )
        self.persistent_cache_hits = Counter(
            f"{ns}_persistent_cache_hits_total",
            "Compiled programs loaded from the persistent cache",
            registry=registry,
        )
        self.persistent_cache_misses = Counter(
            f"{ns}_persistent_cache_misses_total",
            "Programs the persistent cache did not hold (cold compile)",
            registry=registry,
        )
        self.warm_manifest_fresh = Gauge(
            f"{ns}_warm_manifest_fresh",
            "1 if every AOT-registered program was warm at pool start "
            "(manifest fresh for this backend/jax/source)",
            registry=registry,
        )
        self.warm_programs_total = Gauge(
            f"{ns}_warm_programs_registered",
            "AOT-registered programs for this node's dispatch set",
            registry=registry,
        )
        self.warm_programs_warm = Gauge(
            f"{ns}_warm_programs_warm",
            "AOT-registered programs present + fresh at pool start",
            registry=registry,
        )
        # Fault-domain observability (chain/bls/breaker.py + the
        # degradation ladder in device_pool.py): a node quietly serving
        # verdicts off the host fallback must be visible on a dashboard,
        # not discovered in a post-mortem.
        self.device_faults = Counter(
            f"{ns}_device_faults_total",
            "Device dispatch exceptions (XLA runtime/compile errors; "
            "verification verdicts of False are NOT counted here)",
            registry=registry,
        )
        self.degraded_jobs = Counter(
            f"{ns}_degraded_jobs_total",
            "Jobs that engaged a degradation tier beyond the batch "
            "kernel (tier: device_retry | per_set | host)",
            labelnames=("tier",),
            registry=registry,
        )
        self.breaker_state = Gauge(
            f"{ns}_breaker_state",
            "Device circuit-breaker state (0 closed / 1 half-open / 2 open)",
            registry=registry,
        )
        self.breaker_trips = Counter(
            f"{ns}_breaker_trips_total",
            "Circuit-breaker trips (closed/half-open -> open)",
            registry=registry,
        )
        self.breaker_probes = Counter(
            f"{ns}_breaker_probes_total",
            "Half-open canary jobs admitted to the device",
            registry=registry,
        )
        self.breaker_short_circuits = Counter(
            f"{ns}_breaker_short_circuited_jobs_total",
            "Jobs routed straight to the host verifier while the "
            "breaker was open",
            registry=registry,
        )
        self.persistent_cache_load_errors = Counter(
            f"{ns}_persistent_cache_load_errors_total",
            "Persistent-cache entries that existed but failed to "
            "deserialize (quarantined + recompiled; see docs/AOT.md)",
            registry=registry,
        )

    @classmethod
    def get(cls) -> "BlsPoolMetrics":
        if cls._instance is None:
            cls._instance = cls()
        return cls._instance
