"""Device BLS verifier pool — TPU replacement for the worker-thread pool.

Reference semantics (packages/beacon-node/src/chain/bls/multithread/):
  * batchable sets buffer up to MAX_BUFFERED_SIGS=32 or MAX_BUFFER_WAIT_MS=
    100 ms, whichever first (index.ts:48,57)
  * at most MAX_SIGNATURE_SETS_PER_JOB=128 sets per device job (index.ts:39)
  * a failed batch falls back to per-set verification — here a single
    vmapped kernel instead of the worker's serial loop (worker.ts:76-98)
  * non-batchable requests dispatch immediately

The "pool" is the device itself: jobs run one at a time on the chip via an
asyncio lock (XLA serializes kernels anyway), with the batching window
amortizing dispatch + padded-bucket compile reuse (16/32/64/128).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from lodestar_tpu.crypto.bls.api import SignatureSet, verify_signature_set
from .interface import VerifyOptions
from .metrics import BlsPoolMetrics

# Default job size matches the reference's per-worker cap (index.ts:39).
# On TPU the Pallas kernels keep batch latency nearly flat to ~512 sets,
# so the verifier accepts a larger cap via the constructor for
# throughput-bound deployments (sync, bursty gossip).
MAX_SIGNATURE_SETS_PER_JOB = 128
MAX_BUFFERED_SIGS = 32
MAX_BUFFER_WAIT_MS = 100


@dataclass
class _BufferedJob:
    sets: List[SignatureSet]
    future: "asyncio.Future[bool]"
    added_at: float


class DeviceBlsVerifier:
    """Batched device verification behind the IBlsVerifier boundary."""

    def __init__(
        self,
        metrics: Optional[BlsPoolMetrics] = None,
        _backend=None,
        max_sets_per_job: int = MAX_SIGNATURE_SETS_PER_JOB,
    ):
        # _backend injection point for tests (defaults to the jit kernels)
        if _backend is None:
            from lodestar_tpu.ops.bls12_381 import verify as dv

            _backend = dv
        self._dv = _backend
        self._max_sets_per_job = max_sets_per_job
        self._buffer: List[_BufferedJob] = []
        self._buffer_sigs = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._device_lock = asyncio.Lock()
        self._metrics = metrics
        self._closed = False
        # strong refs: the event loop only weakly references tasks, and a
        # GC'd job task would strand its waiters forever
        self._tasks: set = set()

    # ------------------------------------------------------------------

    async def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: VerifyOptions = VerifyOptions()
    ) -> bool:
        if self._closed:
            raise RuntimeError("verifier closed")
        if not sets:
            return False
        if opts.verify_on_main_thread:
            return all(verify_signature_set(s) for s in sets)

        if opts.batchable and len(sets) <= self._max_sets_per_job:
            return await self._enqueue(list(sets))

        # non-batchable or oversized: dispatch now, chunked to job size
        results = []
        for i in range(0, len(sets), self._max_sets_per_job):
            chunk = list(sets[i : i + self._max_sets_per_job])
            results.append(await self._run_job([_make_job(chunk)]))
        return all(results)

    async def close(self) -> None:
        self._closed = True
        if self._flush_handle:
            self._flush_handle.cancel()
            self._flush_handle = None
        for job in self._buffer:
            if not job.future.done():
                job.future.set_exception(RuntimeError("verifier closed"))
        self._buffer.clear()
        self._buffer_sigs = 0

    # ------------------------------------------------------------------

    async def _enqueue(self, sets: List[SignatureSet]) -> bool:
        loop = asyncio.get_running_loop()
        job = _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
        self._buffer.append(job)
        self._buffer_sigs += len(sets)
        if self._metrics:
            self._metrics.job_queue_length.set(self._buffer_sigs)
        if self._buffer_sigs >= MAX_BUFFERED_SIGS:
            self._schedule_flush(0)
        elif self._flush_handle is None:
            self._schedule_flush(MAX_BUFFER_WAIT_MS / 1000)
        return await job.future

    def _schedule_flush(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush_handle = loop.call_later(delay, self._flush)

    def _flush(self) -> None:
        self._flush_handle = None
        if not self._buffer:
            return
        jobs, self._buffer = self._buffer, []
        self._buffer_sigs = 0
        if self._metrics:
            self._metrics.job_queue_length.set(0)
        # pack buffered jobs into device jobs of <= 128 sets
        packs: List[List[_BufferedJob]] = [[]]
        count = 0
        for job in jobs:
            if count + len(job.sets) > self._max_sets_per_job and packs[-1]:
                packs.append([])
                count = 0
            packs[-1].append(job)
            count += len(job.sets)
        for pack in packs:
            task = asyncio.ensure_future(self._run_pack(pack))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    async def _run_pack(self, pack: List[_BufferedJob]) -> None:
        try:
            await self._run_job(pack)
        except Exception as e:  # propagate to waiters
            for job in pack:
                if not job.future.done():
                    job.future.set_exception(e)

    async def _run_job(self, pack: List[_BufferedJob]) -> bool:
        """Run one device job for a pack of requests; resolves each
        request's future.  Returns the AND of all results (for the
        immediate-dispatch path)."""
        all_sets: List[SignatureSet] = []
        for job in pack:
            all_sets.extend(job.sets)
        now = time.monotonic()
        if self._metrics:
            self._metrics.jobs_started.inc()
            self._metrics.sig_sets_total.inc(len(all_sets))
            for job in pack:
                self._metrics.job_wait_time.observe(now - job.added_at)

        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        async with self._device_lock:
            batch_ok = await loop.run_in_executor(
                None, self._dv.verify_signature_sets_device, all_sets
            )
            if batch_ok:
                per_set: Optional[List[bool]] = None
            else:
                # batch failed: one vmapped per-set pass splits good from bad
                if self._metrics:
                    self._metrics.batch_retries.inc()
                per_set = await loop.run_in_executor(
                    None, self._dv.verify_each_device, all_sets
                )
        if self._metrics:
            self._metrics.job_run_time.observe(time.monotonic() - t0)

        # resolve each buffered request
        ok_all = True
        offset = 0
        for job in pack:
            n = len(job.sets)
            if per_set is None:
                ok = True
            else:
                ok = all(per_set[offset : offset + n])
            offset += n
            if self._metrics and not ok:
                self._metrics.invalid_sets.inc()
            if not job.future.done():
                job.future.set_result(ok)
            ok_all = ok_all and ok
        return ok_all


def _make_job(sets: List[SignatureSet]) -> _BufferedJob:
    loop = asyncio.get_running_loop()
    return _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
