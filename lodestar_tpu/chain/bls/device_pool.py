"""Device BLS verifier pool — TPU replacement for the worker-thread pool.

Reference semantics (packages/beacon-node/src/chain/bls/multithread/):
  * batchable sets buffer up to MAX_BUFFERED_SIGS=32 or MAX_BUFFER_WAIT_MS=
    100 ms, whichever first (index.ts:48,57)
  * at most MAX_SIGNATURE_SETS_PER_JOB=128 sets per device job (index.ts:39)
  * a failed batch falls back to per-set verification — here a single
    vmapped kernel instead of the worker's serial loop (worker.ts:76-98)
  * non-batchable requests dispatch immediately

The "pool" is the device itself: jobs run one at a time on the chip via an
asyncio lock (XLA serializes kernels anyway), with the batching window
amortizing dispatch + padded-bucket compile reuse (16/32/64/128).
"""
from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from lodestar_tpu.crypto.bls.api import SignatureSet, verify_signature_set
from lodestar_tpu.ops.bls12_381 import buckets as bk
from lodestar_tpu.utils import gather_settled
from .interface import VerifyOptions
from .metrics import BlsPoolMetrics

# The reference's per-worker cap is 128 sets/job (index.ts:39) — the
# right shape for a CPU thread.  The TPU kernel's batch latency is
# dominated by a ~350 ms sequential-scan floor and grows only mildly
# with width (measured r4: 628 ms at B=1024, ~1 s at 4096), so the
# device wants MUCH larger, LOAD-ADAPTIVE jobs: dispatch is work-
# conserving (one job in flight; when the device frees, the whole
# backlog becomes the next job, up to the cap).  Job width then
# self-regulates to arrival rate x job time — ~500 sets at the
# BASELINE per-slot firehose — while the cap bounds worst-case job
# latency.  The reference-mirror constant is kept for comparison.
REFERENCE_SETS_PER_JOB = 128
MAX_SIGNATURE_SETS_PER_JOB = 2048
MAX_BUFFER_WAIT_MS = 100

# Latency governor (VERDICT r4 #3: cap job width so kernel latency stays
# inside the gossip budget).  The kernel latency model t(B) = FLOOR +
# PER_SET*B is the r4 builder-session fit (628 ms @1024, ~1 s @4096 —
# re-fit from the next driver-visible bench).  A request's worst case is
# waiting out the in-flight job plus its own, so steady-state width is
# capped where t(width) <= budget/2; when the backlog exceeds the cap
# the pool is in overload — every extra request would miss the budget
# anyway, so it reverts to max-width jobs (throughput-optimal drain).
LATENCY_BUDGET_S = 1.0
MODEL_FLOOR_S = 0.35
MODEL_PER_SET_S = 0.00017
MIN_JOB_WIDTH = 128


def governed_steady_width(max_sets_per_job: int = MAX_SIGNATURE_SETS_PER_JOB) -> int:
    """Steady-state governed job width, aligned UP to the pool's
    compile rung: the raw model width (e.g. 882) already pads to the
    1024-bucket program at dispatch, so jobs up to the full rung cost
    the device EXACTLY the same padded program while serving more sets
    — aligning down instead would cut steady throughput ~30% for no
    latency gain.  ops/bls12_381/buckets.py is the shared source of the
    rung geometry and the AOT warm registry compiles exactly these, so
    the governor can never mint a program shape the warm tool does not
    know about."""
    budget_width = int((LATENCY_BUDGET_S / 2 - MODEL_FLOOR_S) / MODEL_PER_SET_S)
    raw = min(max_sets_per_job, max(MIN_JOB_WIDTH, budget_width))
    # pool_bucket respects a tiny explicit cap (tests build 1-8 set
    # pools, which fall back to the direct ladder) via min() below
    return min(max_sets_per_job, bk.pool_bucket(raw, cap=max_sets_per_job))


@dataclass
class _BufferedJob:
    sets: List[SignatureSet]
    future: "asyncio.Future[bool]"
    added_at: float


class DeviceBlsVerifier:
    """Batched device verification behind the IBlsVerifier boundary."""

    def __init__(
        self,
        metrics: Optional[BlsPoolMetrics] = None,
        _backend=None,
        max_sets_per_job: int = MAX_SIGNATURE_SETS_PER_JOB,
    ):
        # _backend injection point for tests (defaults to the jit kernels)
        is_production_backend = _backend is None
        if _backend is None:
            # production node path: enable the persistent compilation
            # cache BEFORE the first kernel dispatch — previously the
            # node never configured it and paid a full cold compile
            # every process start (ISSUE 5)
            from lodestar_tpu.aot import cache as aot_cache

            aot_cache.configure()
            from lodestar_tpu.ops.bls12_381 import verify as dv

            _backend = dv
        self._dv = _backend
        self._max_sets_per_job = max_sets_per_job
        self._buffer: List[_BufferedJob] = []
        self._buffer_sigs = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        # pipeline stage flag: a pack owns the host ENCODE stage from
        # dispatch until it acquires the device; the device itself is
        # serialized by _device_lock, so encode of pack N+1 overlaps
        # device execution of pack N
        self._encoding = False
        self._device_lock = asyncio.Lock()
        self._metrics = metrics
        self._closed = False
        # strong refs: the event loop only weakly references tasks, and a
        # GC'd job task would strand its waiters forever
        self._tasks: set = set()
        self._cache_spy_cb = None
        # only the production jit backend compiles programs: wiring the
        # spy + warm-manifest check for a fake test backend would drag
        # jax (backend init, source-tree hashing) into tests for nothing
        if metrics is not None and is_production_backend:
            self._wire_compile_observability(metrics)

    # ------------------------------------------------------------------

    async def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: VerifyOptions = VerifyOptions()
    ) -> bool:
        if self._closed:
            raise RuntimeError("verifier closed")
        if not sets:
            return False
        if opts.verify_on_main_thread:
            return all(verify_signature_set(s) for s in sets)

        if opts.batchable and len(sets) <= self._max_sets_per_job:
            # a single wide request would bypass the latency governor
            # (a buffered job is never split at flush time), so chunk it
            # to the governed width HERE and AND the chunk results
            cap = self._steady_width_cap()
            if len(sets) <= cap:
                return await self._enqueue(list(sets))
            chunks = [list(sets[i : i + cap]) for i in range(0, len(sets), cap)]
            # settle every chunk before reporting, so a failing chunk
            # can't leave detached siblings with unretrieved exceptions
            # (ADVICE r5)
            return all(
                await gather_settled(*(self._enqueue(c) for c in chunks))
            )

        # non-batchable or oversized: dispatch now, chunked to the
        # governed width.  All jobs serialize on the device, so a
        # max-width immediate job would hold queued-path bystanders past
        # the budget the governor guarantees (worst case = in-flight +
        # own job, each <= budget/2).  The oversized caller pays the
        # per-chunk dispatch floor — that is the accepted price of the
        # bystander guarantee.
        cap = self._steady_width_cap()
        results = []
        for i in range(0, len(sets), cap):
            chunk = list(sets[i : i + cap])
            results.append(await self._run_job([_make_job(chunk)]))
        return all(results)

    async def close(self) -> None:
        """Cancel-and-settle: buffered requests are failed immediately,
        in-flight job tasks are cancelled and AWAITED so close cannot
        strand a running device job's waiters or leave its executor
        call unobserved (_run_pack settles its pack's futures on
        cancellation before re-raising)."""
        self._closed = True
        if self._flush_handle:
            self._flush_handle.cancel()
            self._flush_handle = None
        for job in self._buffer:
            if not job.future.done():
                job.future.set_exception(RuntimeError("verifier closed"))
        self._buffer.clear()
        self._buffer_sigs = 0
        tasks = [t for t in self._tasks if not t.done()]
        for t in tasks:
            t.cancel()
        if tasks:
            # settle every cancelled task; exceptions (incl. the
            # CancelledErrors we just caused) are retrieved here, not
            # left to the loop's unhandled-exception logger
            await asyncio.gather(*tasks, return_exceptions=True)
        if self._cache_spy_cb is not None:
            # release the process-global spy's strong ref to this pool
            # (a restarted node would otherwise multiply-count every
            # cache event into the shared metrics singleton)
            from lodestar_tpu.aot import cache as aot_cache

            aot_cache.remove_cache_spy_callback(self._cache_spy_cb)
            self._cache_spy_cb = None

    def _wire_compile_observability(self, metrics: BlsPoolMetrics) -> None:
        """Feed persistent-cache hit/miss + compile-time events into the
        Prometheus family and publish warm-manifest freshness (tentpole
        observability: a node operator can SEE whether first-verify will
        compile cold).  Best-effort: a fake backend without jax present
        must not break pool construction."""
        try:
            from lodestar_tpu.aot import cache as aot_cache

            aot_cache.install_cache_spy(self._on_cache_event)
            self._cache_spy_cb = self._on_cache_event
        except Exception:
            return

        def _freshness() -> None:
            # backend init + a source-tree fingerprint walk cost
            # seconds: off the constructing thread (typically the event
            # loop during node startup).  prometheus gauges are
            # thread-safe; the values land moments after construction.
            try:
                from lodestar_tpu.aot import registry, warm

                ok, rows = warm.check_programs(registry.registered_programs())
                metrics.warm_manifest_fresh.set(1 if ok else 0)
                metrics.warm_programs_total.set(len(rows))
                metrics.warm_programs_warm.set(
                    sum(1 for _, s in rows if s == "warm")
                )
            except Exception:
                # no jax / no manifest yet: freshness is unknown-cold
                metrics.warm_manifest_fresh.set(0)

        threading.Thread(
            target=_freshness, name="bls-warm-freshness", daemon=True
        ).start()

    def _on_cache_event(self, kind: str, cache_key: str, seconds: float) -> None:
        m = self._metrics
        if m is None:
            return
        if kind == "hit":
            m.persistent_cache_hits.inc()
        elif kind == "miss":
            m.persistent_cache_misses.inc()
        elif kind == "put":
            m.compile_time.observe(seconds)

    # ------------------------------------------------------------------

    async def _enqueue(self, sets: List[SignatureSet]) -> bool:
        loop = asyncio.get_running_loop()
        job = _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
        self._buffer.append(job)
        self._buffer_sigs += len(sets)
        if self._metrics:
            self._metrics.job_queue_length.set(self._buffer_sigs)
        # Latency-bounded flush: dispatch immediately once a full device
        # job is buffered, otherwise wait up to MAX_BUFFER_WAIT_MS for
        # more sets (amortizing the kernel's fixed sequential-scan cost
        # over the widest batch the window collects).  The reference
        # flushes at 32 sigs (index.ts:48) because its workers saturate
        # early; the device's throughput grows with width instead.
        if self._buffer_sigs >= self._latency_width_cap():
            self._schedule_flush(0)
        elif self._flush_handle is None:
            self._schedule_flush(MAX_BUFFER_WAIT_MS / 1000)
        return await job.future

    def _steady_width_cap(self) -> int:
        """Width where t(width) <= LATENCY_BUDGET_S/2 under the fitted
        latency model (worst case = in-flight job + own job), aligned
        UP to the pool compile rung the raw width would pad into anyway
        so the governor can only produce program shapes the AOT warm
        registry compiled.  MIN_JOB_WIDTH
        floors the model-derived width (a degenerate fit must not
        trickle tiny jobs) but never overrides an explicitly smaller
        pool cap (tests construct 8-set pools)."""
        return governed_steady_width(self._max_sets_per_job)

    def _latency_width_cap(self) -> int:
        """Steady-state governed width — unless the backlog already
        exceeds what capped jobs can clear in-budget, which is overload:
        revert to max-width drain (throughput-optimal, bucket-aligned).
        The threshold is at least one full max job so a single wide
        request's chunks (just gathered by verify_signature_sets) cannot
        flip the pool into overload and re-fuse themselves into one
        over-budget job."""
        cap = self._steady_width_cap()
        # threshold: a full max-size request's chunks PLUS a capped job's
        # worth of bystanders must not count as overload (else the just-
        # chunked request re-fuses into one over-budget job)
        if self._buffer_sigs > self._max_sets_per_job + cap:
            return bk.align_down(self._max_sets_per_job)
        return cap

    def _schedule_flush(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush_handle = loop.call_later(delay, self._flush)

    def _flush(self) -> None:
        """Work-conserving dispatch: take ONE pack (the whole backlog,
        up to the job cap) and run it; remaining requests stay buffered
        and become the next job the moment the ENCODE stage frees (not
        the device: pack N+1 encodes on the host executor while pack N
        holds the device lock).  Under load the job width adapts to
        arrival_rate x stage_time instead of trickling fixed-size jobs
        through the window."""
        self._flush_handle = None
        if self._closed or not self._buffer or self._encoding:
            return
        width_cap = self._latency_width_cap()
        if self._device_lock.locked() and self._buffer_sigs < width_cap:
            # The device is busy and the backlog can't fill a full-width
            # pack: forming a partial pack EARLY would pay an extra
            # kernel floor and deepen worst-case queueing for zero
            # throughput gain — only full-width packs are worth encoding
            # ahead of the device.  Re-arm the window; the running
            # pack's completion (or the backlog reaching full width)
            # re-triggers us sooner.
            self._schedule_flush(MAX_BUFFER_WAIT_MS / 1000)
            return
        pack: List[_BufferedJob] = []
        count = 0
        while self._buffer:
            job = self._buffer[0]
            if pack and count + len(job.sets) > width_cap:
                break
            pack.append(self._buffer.pop(0))
            count += len(job.sets)
        self._buffer_sigs -= count
        if self._metrics:
            self._metrics.job_queue_length.set(self._buffer_sigs)
        self._encoding = True
        task = asyncio.ensure_future(self._run_pack(pack))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _release_encode(self) -> None:
        """Free the encode stage and wake the next pack.  Callers track
        ownership (a pack releases exactly once — the moment it
        transitions encode -> device, or from _run_pack's finally if it
        failed before reaching the lock)."""
        self._encoding = False
        if self._buffer and not self._closed:
            self._schedule_flush(0)

    async def _run_pack(self, pack: List[_BufferedJob]) -> None:
        # ownership token for the encode stage: _run_job clears it when
        # the pack reaches the device; if we still hold it in finally,
        # the pack died during encode and must free the stage itself
        owns = {"encode": True}
        try:
            await self._run_job(pack, encode_owner=owns)
        except asyncio.CancelledError:
            # close() cancel-and-settle: fail the pack's waiters, then
            # let the cancellation propagate to the gather in close()
            for job in pack:
                if not job.future.done():
                    job.future.set_exception(RuntimeError("verifier closed"))
            raise
        except Exception as e:  # propagate to waiters
            for job in pack:
                if not job.future.done():
                    job.future.set_exception(e)
        finally:
            if owns["encode"]:
                owns["encode"] = False
                self._release_encode()
            if self._buffer and not self._closed:
                self._schedule_flush(0)

    async def _run_job(
        self, pack: List[_BufferedJob], encode_owner: Optional[dict] = None
    ) -> bool:
        """Run one device job for a pack of requests; resolves each
        request's future.  Returns the AND of all results (for the
        immediate-dispatch path).

        Two pipeline stages: host ENCODE (expand_message_xmd, field-draw
        reduction, limb packing) runs on the executor BEFORE taking the
        device lock; the encode stage is released the moment the device
        lock is acquired, so the next pack's encode overlaps this one's
        device execution while at most one encoded pack waits at the
        lock (bounded pipeline depth, keeps the governor's worst-case
        latency model honest)."""
        all_sets: List[SignatureSet] = []
        for job in pack:
            all_sets.extend(job.sets)
        now = time.monotonic()
        if self._metrics:
            self._metrics.jobs_started.inc()
            self._metrics.sig_sets_total.inc(len(all_sets))
            for job in pack:
                self._metrics.job_wait_time.observe(now - job.added_at)

        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        bucket = bk.pool_bucket(len(all_sets), cap=self._max_sets_per_job)
        encoded = await loop.run_in_executor(
            None, lambda: self._dv.encode_job(all_sets, bucket=bucket)
        )
        if self._metrics:
            self._metrics.encode_time.observe(time.monotonic() - t0)
        async with self._device_lock:
            # we own the device: free the encode stage for pack N+1
            # (only the buffered-flush path owns the encode stage — an
            # immediate-dispatch job must not release someone else's)
            if encode_owner is not None and encode_owner["encode"]:
                encode_owner["encode"] = False
                self._release_encode()
            batch_ok = await loop.run_in_executor(
                None, self._dv.execute_batch, encoded
            )
            if batch_ok:
                per_set: Optional[List[bool]] = None
            else:
                # batch failed: one vmapped per-set pass splits good from bad
                if self._metrics:
                    self._metrics.batch_retries.inc()
                per_set = await loop.run_in_executor(
                    None, lambda: self._dv.verify_each_device(all_sets, bucket=bucket)
                )
        # device released: wake any deferred partial pack NOW.  The
        # buffered path also schedules from _run_pack's finally, but the
        # immediate-dispatch path reaches the lock only through here —
        # without this, back-to-back immediate jobs would keep the lock
        # busy while _flush re-arms its window forever, starving
        # buffered sub-cap requests past the latency budget.
        if self._buffer and not self._closed:
            self._schedule_flush(0)
        if self._metrics:
            self._metrics.job_run_time.observe(time.monotonic() - t0)

        # resolve each buffered request
        ok_all = True
        offset = 0
        for job in pack:
            n = len(job.sets)
            if per_set is None:
                ok = True
            else:
                ok = all(per_set[offset : offset + n])
            offset += n
            if self._metrics and not ok:
                self._metrics.invalid_sets.inc()
            if not job.future.done():
                job.future.set_result(ok)
            ok_all = ok_all and ok
        return ok_all


def _make_job(sets: List[SignatureSet]) -> _BufferedJob:
    loop = asyncio.get_running_loop()
    return _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
