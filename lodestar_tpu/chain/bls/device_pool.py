"""Device BLS verifier pool — TPU replacement for the worker-thread pool.

Reference semantics (packages/beacon-node/src/chain/bls/multithread/):
  * batchable sets buffer up to MAX_BUFFERED_SIGS=32 or MAX_BUFFER_WAIT_MS=
    100 ms, whichever first (index.ts:48,57)
  * at most MAX_SIGNATURE_SETS_PER_JOB=128 sets per device job (index.ts:39)
  * non-batchable requests dispatch immediately

Fault-domain ladder (tiers engage strictly in order, per job):
  1. **device batch** — the padded batch kernel.  A batch VERDICT of
     ``False`` (some set invalid) is not a fault: it goes straight to
     the vmapped per-set kernel to split good from bad, mirroring the
     reference's retry-each-individually (worker.ts:76-98 /
     maybeBatch.ts:17).
  2. **device retry** — a device *exception* (XLA runtime error,
     compile crash) gets ONE immediate re-dispatch; transient faults
     end here.
  3. **device per-set** — if the retry also faults, the vmapped per-set
     kernel (``verify_each_device``, in the AOT warm registry) is tried.
  4. **host** — last resort: the CPU oracle verifies the pack
     (batch-then-per-set, SingleThreadBlsVerifier semantics).  Waiters
     always receive boolean verdicts for device faults; only host-side
     failures (encode bugs, close()) surface as exceptions.
A circuit breaker (chain/bls/breaker.py) watches consecutive
device-fault jobs: after N it trips and packs go straight to tier 4
without paying the device timeout, then a half-open canary job probes
the device on exponential backoff.  Breaker state and per-tier
engagement counters are exported through BlsPoolMetrics.

The "pool" is the device itself: jobs run one at a time on the chip via an
asyncio lock (XLA serializes kernels anyway), with the batching window
amortizing dispatch + padded-bucket compile reuse (16/32/64/128).

Ownership discipline (mechanically enforced by lodelint's
``pool-ownership`` rule, docs/LINT.md): pool state (`_buffer`,
`_buffer_sigs`, `_encoding`, `_flush_handle`, `_tasks`) is owned by the
event loop — callables handed to ``run_in_executor`` (`_encode_host`,
`_execute_device`, `_each_device`, `_host_verify_pack`) never mutate it;
the encode-stage token is released only through the test-and-clear guard
(``if owner["encode"]: owner["encode"] = False; self._release_encode()``)
with no await inside the guard.  Job widths are quantized through
``buckets.pool_bucket`` before any dispatch or ``bucket=`` hand-off, so
every program shape the pool can mint is in the AOT warm registry
(enforced by ``retrace-hazard``).
"""
from __future__ import annotations

import asyncio
import threading
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from lodestar_tpu.crypto.bls.api import SignatureSet, verify_signature_set
from lodestar_tpu.ops.bls12_381 import buckets as bk
from lodestar_tpu.testing import faults
from lodestar_tpu.utils import gather_settled, get_logger
from . import breaker as brk
from .breaker import DeviceCircuitBreaker
from .interface import VerifyOptions
from .metrics import BlsPoolMetrics

# The reference's per-worker cap is 128 sets/job (index.ts:39) — the
# right shape for a CPU thread.  The TPU kernel's batch latency is
# dominated by a ~350 ms sequential-scan floor and grows only mildly
# with width (measured r4: 628 ms at B=1024, ~1 s at 4096), so the
# device wants MUCH larger, LOAD-ADAPTIVE jobs: dispatch is work-
# conserving (one job in flight; when the device frees, the whole
# backlog becomes the next job, up to the cap).  Job width then
# self-regulates to arrival rate x job time — ~500 sets at the
# BASELINE per-slot firehose — while the cap bounds worst-case job
# latency.  The reference-mirror constant is kept for comparison.
REFERENCE_SETS_PER_JOB = 128
MAX_SIGNATURE_SETS_PER_JOB = 2048
MAX_BUFFER_WAIT_MS = 100

# Latency governor (VERDICT r4 #3: cap job width so kernel latency stays
# inside the gossip budget).  The kernel latency model t(B) = FLOOR +
# PER_SET*B is the r4 builder-session fit (628 ms @1024, ~1 s @4096 —
# re-fit from the next driver-visible bench).  A request's worst case is
# waiting out the in-flight job plus its own, so steady-state width is
# capped where t(width) <= budget/2; when the backlog exceeds the cap
# the pool is in overload — every extra request would miss the budget
# anyway, so it reverts to max-width jobs (throughput-optimal drain).
LATENCY_BUDGET_S = 1.0
MODEL_FLOOR_S = 0.35
MODEL_PER_SET_S = 0.00017
MIN_JOB_WIDTH = 128


def governed_steady_width(max_sets_per_job: int = MAX_SIGNATURE_SETS_PER_JOB) -> int:
    """Steady-state governed job width, aligned UP to the pool's
    compile rung: the raw model width (e.g. 882) already pads to the
    1024-bucket program at dispatch, so jobs up to the full rung cost
    the device EXACTLY the same padded program while serving more sets
    — aligning down instead would cut steady throughput ~30% for no
    latency gain.  ops/bls12_381/buckets.py is the shared source of the
    rung geometry and the AOT warm registry compiles exactly these, so
    the governor can never mint a program shape the warm tool does not
    know about."""
    budget_width = int((LATENCY_BUDGET_S / 2 - MODEL_FLOOR_S) / MODEL_PER_SET_S)
    raw = min(max_sets_per_job, max(MIN_JOB_WIDTH, budget_width))
    # pool_bucket respects a tiny explicit cap (tests build 1-8 set
    # pools, which fall back to the direct ladder) via min() below
    return min(max_sets_per_job, bk.pool_bucket(raw, cap=max_sets_per_job))


@dataclass
class _BufferedJob:
    sets: List[SignatureSet]
    future: "asyncio.Future[bool]"
    added_at: float


class DeviceBlsVerifier:
    """Batched device verification behind the IBlsVerifier boundary."""

    def __init__(
        self,
        metrics: Optional[BlsPoolMetrics] = None,
        _backend=None,
        max_sets_per_job: int = MAX_SIGNATURE_SETS_PER_JOB,
        breaker: Optional[DeviceCircuitBreaker] = None,
    ):
        # _backend injection point for tests (defaults to the jit kernels)
        is_production_backend = _backend is None
        if _backend is None:
            # production node path: enable the persistent compilation
            # cache BEFORE the first kernel dispatch — previously the
            # node never configured it and paid a full cold compile
            # every process start (ISSUE 5)
            from lodestar_tpu.aot import cache as aot_cache

            aot_cache.configure()
            from lodestar_tpu.ops.bls12_381 import verify as dv

            _backend = dv
        self._dv = _backend
        self._breaker = breaker if breaker is not None else DeviceCircuitBreaker()
        self._log = get_logger("bls-pool")
        self._max_sets_per_job = max_sets_per_job
        self._buffer: List[_BufferedJob] = []
        self._buffer_sigs = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        # pipeline stage flag: a pack owns the host ENCODE stage from
        # dispatch until it acquires the device; the device itself is
        # serialized by _device_lock, so encode of pack N+1 overlaps
        # device execution of pack N
        self._encoding = False
        self._device_lock = asyncio.Lock()
        self._metrics = metrics
        self._closed = False
        # strong refs: the event loop only weakly references tasks, and a
        # GC'd job task would strand its waiters forever
        self._tasks: set = set()
        self._cache_spy_cb = None
        # only the production jit backend compiles programs: wiring the
        # spy + warm-manifest check for a fake test backend would drag
        # jax (backend init, source-tree hashing) into tests for nothing
        if metrics is not None and is_production_backend:
            self._wire_compile_observability(metrics)

    # ------------------------------------------------------------------

    async def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: VerifyOptions = VerifyOptions()
    ) -> bool:
        if self._closed:
            raise RuntimeError("verifier closed")
        if not sets:
            return False
        if opts.verify_on_main_thread:
            return all(verify_signature_set(s) for s in sets)

        if opts.batchable and len(sets) <= self._max_sets_per_job:
            # a single wide request would bypass the latency governor
            # (a buffered job is never split at flush time), so chunk it
            # to the governed width HERE and AND the chunk results
            cap = self._steady_width_cap()
            if len(sets) <= cap:
                return await self._enqueue(list(sets))
            chunks = [list(sets[i : i + cap]) for i in range(0, len(sets), cap)]
            # settle every chunk before reporting, so a failing chunk
            # can't leave detached siblings with unretrieved exceptions
            # (ADVICE r5)
            return all(
                await gather_settled(*(self._enqueue(c) for c in chunks))
            )

        # non-batchable or oversized: dispatch now, chunked to the
        # governed width.  All jobs serialize on the device, so a
        # max-width immediate job would hold queued-path bystanders past
        # the budget the governor guarantees (worst case = in-flight +
        # own job, each <= budget/2).  The oversized caller pays the
        # per-chunk dispatch floor — that is the accepted price of the
        # bystander guarantee.
        cap = self._steady_width_cap()
        results = []
        for i in range(0, len(sets), cap):
            chunk = list(sets[i : i + cap])
            results.append(await self._run_job([_make_job(chunk)]))
        return all(results)

    async def close(self) -> None:
        """Cancel-and-settle: buffered requests are failed immediately,
        in-flight job tasks are cancelled and AWAITED so close cannot
        strand a running device job's waiters or leave its executor
        call unobserved (_run_pack settles its pack's futures on
        cancellation before re-raising)."""
        self._closed = True
        if self._flush_handle:
            self._flush_handle.cancel()
            self._flush_handle = None
        for job in self._buffer:
            if not job.future.done():
                job.future.set_exception(RuntimeError("verifier closed"))
        self._buffer.clear()
        self._buffer_sigs = 0
        tasks = [t for t in self._tasks if not t.done()]
        for t in tasks:
            t.cancel()
        if tasks:
            # settle every cancelled task; exceptions (incl. the
            # CancelledErrors we just caused) are retrieved here, not
            # left to the loop's unhandled-exception logger
            await asyncio.gather(*tasks, return_exceptions=True)
        if self._cache_spy_cb is not None:
            # release the process-global spy's strong ref to this pool
            # (a restarted node would otherwise multiply-count every
            # cache event into the shared metrics singleton)
            from lodestar_tpu.aot import cache as aot_cache

            aot_cache.remove_cache_spy_callback(self._cache_spy_cb)
            self._cache_spy_cb = None

    def _wire_compile_observability(self, metrics: BlsPoolMetrics) -> None:
        """Feed persistent-cache hit/miss + compile-time events into the
        Prometheus family and publish warm-manifest freshness (tentpole
        observability: a node operator can SEE whether first-verify will
        compile cold).  Best-effort: a fake backend without jax present
        must not break pool construction."""
        try:
            from lodestar_tpu.aot import cache as aot_cache

            aot_cache.install_cache_spy(self._on_cache_event)
            self._cache_spy_cb = self._on_cache_event
        except Exception as e:
            self._log.debug(
                f"persistent-cache spy unavailable "
                f"({type(e).__name__}: {e}); compile observability off"
            )
            return

        def _freshness() -> None:
            # backend init + a source-tree fingerprint walk cost
            # seconds: off the constructing thread (typically the event
            # loop during node startup).  prometheus gauges are
            # thread-safe; the values land moments after construction.
            try:
                from lodestar_tpu.aot import registry, warm

                # check_hashes=False: the gauge needs freshness, not
                # byte integrity — hashing every entry file reads
                # hundreds of MB at pool start on a 2-core host
                ok, rows = warm.check_programs(
                    registry.registered_programs(), check_hashes=False
                )
                metrics.warm_manifest_fresh.set(1 if ok else 0)
                metrics.warm_programs_total.set(len(rows))
                metrics.warm_programs_warm.set(
                    sum(1 for _, s in rows if s == "warm")
                )
            except Exception:
                # no jax / no manifest yet: freshness is unknown-cold
                metrics.warm_manifest_fresh.set(0)

        threading.Thread(
            target=_freshness, name="bls-warm-freshness", daemon=True
        ).start()

    def _on_cache_event(self, kind: str, cache_key: str, seconds: float) -> None:
        m = self._metrics
        if m is None:
            return
        if kind == "hit":
            m.persistent_cache_hits.inc()
        elif kind == "miss":
            m.persistent_cache_misses.inc()
        elif kind == "put":
            m.compile_time.observe(seconds)
        elif kind == "load_error":
            # poisoned persistent-cache entry: the spy quarantined it
            # and jax recompiled (aot/cache.py self-heal path)
            m.persistent_cache_load_errors.inc()

    # ------------------------------------------------------------------

    async def _enqueue(self, sets: List[SignatureSet]) -> bool:
        loop = asyncio.get_running_loop()
        job = _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
        self._buffer.append(job)
        self._buffer_sigs += len(sets)
        if self._metrics:
            self._metrics.job_queue_length.set(self._buffer_sigs)
        # Latency-bounded flush: dispatch immediately once a full device
        # job is buffered, otherwise wait up to MAX_BUFFER_WAIT_MS for
        # more sets (amortizing the kernel's fixed sequential-scan cost
        # over the widest batch the window collects).  The reference
        # flushes at 32 sigs (index.ts:48) because its workers saturate
        # early; the device's throughput grows with width instead.
        if self._buffer_sigs >= self._latency_width_cap():
            self._schedule_flush(0)
        elif self._flush_handle is None:
            self._schedule_flush(MAX_BUFFER_WAIT_MS / 1000)
        return await job.future

    def _steady_width_cap(self) -> int:
        """Width where t(width) <= LATENCY_BUDGET_S/2 under the fitted
        latency model (worst case = in-flight job + own job), aligned
        UP to the pool compile rung the raw width would pad into anyway
        so the governor can only produce program shapes the AOT warm
        registry compiled.  MIN_JOB_WIDTH
        floors the model-derived width (a degenerate fit must not
        trickle tiny jobs) but never overrides an explicitly smaller
        pool cap (tests construct 8-set pools)."""
        return governed_steady_width(self._max_sets_per_job)

    def _latency_width_cap(self) -> int:
        """Steady-state governed width — unless the backlog already
        exceeds what capped jobs can clear in-budget, which is overload:
        revert to max-width drain (throughput-optimal, bucket-aligned).
        The threshold is at least one full max job so a single wide
        request's chunks (just gathered by verify_signature_sets) cannot
        flip the pool into overload and re-fuse themselves into one
        over-budget job."""
        cap = self._steady_width_cap()
        # threshold: a full max-size request's chunks PLUS a capped job's
        # worth of bystanders must not count as overload (else the just-
        # chunked request re-fuses into one over-budget job)
        if self._buffer_sigs > self._max_sets_per_job + cap:
            return bk.align_down(self._max_sets_per_job)
        return cap

    def _schedule_flush(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush_handle = loop.call_later(delay, self._flush)

    def _flush(self) -> None:
        """Work-conserving dispatch: take ONE pack (the whole backlog,
        up to the job cap) and run it; remaining requests stay buffered
        and become the next job the moment the ENCODE stage frees (not
        the device: pack N+1 encodes on the host executor while pack N
        holds the device lock).  Under load the job width adapts to
        arrival_rate x stage_time instead of trickling fixed-size jobs
        through the window."""
        self._flush_handle = None
        if self._closed or not self._buffer or self._encoding:
            return
        width_cap = self._latency_width_cap()
        if (
            self._device_lock.locked()
            and self._buffer_sigs < width_cap
            and self._breaker.state == brk.CLOSED
        ):
            # The device is busy and the backlog can't fill a full-width
            # pack: forming a partial pack EARLY would pay an extra
            # kernel floor and deepen worst-case queueing for zero
            # throughput gain — only full-width packs are worth encoding
            # ahead of the device.  Re-arm the window; the running
            # pack's completion (or the backlog reaching full width)
            # re-triggers us sooner.  ONLY while the breaker is CLOSED:
            # open-state packs (and half-open bystanders of a wedged
            # canary) go to the host verifier and never touch the
            # device — deferring them behind a wedged device job would
            # stall sub-cap traffic for exactly as long as the
            # short-circuit promises not to.
            self._schedule_flush(MAX_BUFFER_WAIT_MS / 1000)
            return
        pack: List[_BufferedJob] = []
        count = 0
        while self._buffer:
            job = self._buffer[0]
            if pack and count + len(job.sets) > width_cap:
                break
            pack.append(self._buffer.pop(0))
            count += len(job.sets)
        self._buffer_sigs -= count
        if self._metrics:
            self._metrics.job_queue_length.set(self._buffer_sigs)
        self._encoding = True
        task = asyncio.ensure_future(self._run_pack(pack))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    def _release_encode(self) -> None:
        """Free the encode stage and wake the next pack.  Callers track
        ownership (a pack releases exactly once — the moment it
        transitions encode -> device, or from _run_pack's finally if it
        failed before reaching the lock)."""
        self._encoding = False
        if self._buffer and not self._closed:
            self._schedule_flush(0)

    async def _run_pack(self, pack: List[_BufferedJob]) -> None:
        # ownership token for the encode stage: _run_job clears it when
        # the pack reaches the device; if we still hold it in finally,
        # the pack died during encode and must free the stage itself
        owns = {"encode": True}
        try:
            await self._run_job(pack, encode_owner=owns)
        except asyncio.CancelledError:
            # close() cancel-and-settle: fail the pack's waiters, then
            # let the cancellation propagate to the gather in close()
            for job in pack:
                if not job.future.done():
                    job.future.set_exception(RuntimeError("verifier closed"))
            raise
        except Exception as e:  # propagate to waiters
            for job in pack:
                if not job.future.done():
                    job.future.set_exception(e)
        finally:
            if owns["encode"]:
                owns["encode"] = False
                self._release_encode()
            if self._buffer and not self._closed:
                self._schedule_flush(0)

    async def _run_job(
        self, pack: List[_BufferedJob], encode_owner: Optional[dict] = None
    ) -> bool:
        """Run one device job for a pack of requests; resolves each
        request's future.  Returns the AND of all results (for the
        immediate-dispatch path).

        Two pipeline stages: host ENCODE (expand_message_xmd, field-draw
        reduction, limb packing) runs on the executor BEFORE taking the
        device lock; the encode stage is released the moment the device
        lock is acquired, so the next pack's encode overlaps this one's
        device execution while at most one encoded pack waits at the
        lock (bounded pipeline depth, keeps the governor's worst-case
        latency model honest)."""
        all_sets: List[SignatureSet] = []
        for job in pack:
            all_sets.extend(job.sets)
        now = time.monotonic()
        if self._metrics:
            self._metrics.jobs_started.inc()
            self._metrics.sig_sets_total.inc(len(all_sets))
            for job in pack:
                self._metrics.job_wait_time.observe(now - job.added_at)

        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        bucket = bk.pool_bucket(len(all_sets), cap=self._max_sets_per_job)
        # breaker decision comes BEFORE the encode stage: while the
        # breaker is open the pack goes to the host verifier, which
        # never touches the encoded tensors — paying the device encode
        # (expand_message_xmd + limb packing) would double the host CPU
        # cost exactly when the host is already carrying verification
        decision = self._breaker.allow_device()
        probe_token = (
            self._breaker.probe_token if decision == "canary" else None
        )
        try:
            if decision == "host":
                # breaker open: no encode, and no device lock either —
                # the short-circuit exists to NOT wait on the chip, and
                # a wedged in-flight device job may hold the lock for
                # its whole multi-second failure ladder.  Free the
                # encode stage now (this pack never uses it) and serve
                # the verdicts from the host oracle directly.
                if encode_owner is not None and encode_owner["encode"]:
                    encode_owner["encode"] = False
                    self._release_encode()
                per_set = await self._verify_with_ladder(
                    loop, decision, None, all_sets, bucket
                )
            else:
                encoded = await loop.run_in_executor(
                    None, self._encode_host, all_sets, bucket
                )
                if self._metrics:
                    self._metrics.encode_time.observe(time.monotonic() - t0)
                async with self._device_lock:
                    # we own the device: free the encode stage for pack
                    # N+1 (only the buffered-flush path owns the encode
                    # stage — an immediate-dispatch job must not release
                    # someone else's)
                    if encode_owner is not None and encode_owner["encode"]:
                        encode_owner["encode"] = False
                        self._release_encode()
                    per_set = await self._verify_with_ladder(
                        loop, decision, encoded, all_sets, bucket
                    )
        except BaseException:
            # anything escaping before the probe's outcome landed —
            # close() cancellation, an encode-stage fault — must not
            # leak the half-open canary slot forever.  The token scopes
            # the release to THIS job's probe: once this canary was
            # resolved (or a newer one admitted), cancel_probe is a
            # no-op, so this over-approximates safely.
            if decision == "canary":
                self._breaker.cancel_probe(probe_token)
            raise
        # device released: wake any deferred partial pack NOW.  The
        # buffered path also schedules from _run_pack's finally, but the
        # immediate-dispatch path reaches the lock only through here —
        # without this, back-to-back immediate jobs would keep the lock
        # busy while _flush re-arms its window forever, starving
        # buffered sub-cap requests past the latency budget.
        if self._buffer and not self._closed:
            self._schedule_flush(0)
        if self._metrics:
            self._metrics.job_run_time.observe(time.monotonic() - t0)

        # resolve each buffered request
        ok_all = True
        offset = 0
        for job in pack:
            n = len(job.sets)
            if per_set is None:
                ok = True
            else:
                ok = all(per_set[offset : offset + n])
            offset += n
            if self._metrics and not ok:
                self._metrics.invalid_sets.inc()
            if not job.future.done():
                job.future.set_result(ok)
            ok_all = ok_all and ok
        return ok_all

    # ------------------------------------------------------------------
    # multi-chip sharded path (ROADMAP item 3)
    # ------------------------------------------------------------------

    def sharded_verify_fn(self, mesh):
        """The jitted manual-collectives sharded verification program
        for ``mesh`` (ops/bls12_381/sharded.py) — the multi-chip twin
        of ``_execute_device``'s single-device kernel.  Memoized per
        geometry by the sharded module, so repeated calls share one
        trace cache; dispatch widths must come from
        ``sharded.SHARDED_BUCKETS`` (lodelint's shard-divisibility
        gate pins the geometry contract)."""
        from lodestar_tpu.ops.bls12_381 import sharded

        return sharded.jitted_for_mesh(mesh)

    # ------------------------------------------------------------------
    # degradation ladder (tentpole: waiters get verdicts, not exceptions)
    # ------------------------------------------------------------------

    def _encode_host(self, all_sets: List[SignatureSet], bucket: int):
        faults.fire("bls.host.encode")
        return self._dv.encode_job(all_sets, bucket=bucket)

    def _execute_device(self, encoded):
        faults.fire("bls.device.execute")
        return self._dv.execute_batch(encoded)

    def _each_device(self, all_sets: List[SignatureSet], bucket: int):
        faults.fire("bls.device.each")
        return self._dv.verify_each_device(all_sets, bucket=bucket)

    @staticmethod
    def _host_verify_pack(all_sets: List[SignatureSet]) -> Optional[List[bool]]:
        """CPU oracle verdicts for a pack (SingleThreadBlsVerifier
        semantics: one batched check, per-set split only on failure)."""
        from lodestar_tpu.crypto.bls.api import verify_multiple_signature_sets

        if verify_multiple_signature_sets(list(all_sets)):
            return None
        return [verify_signature_set(s) for s in all_sets]

    async def _verify_with_ladder(
        self, loop, decision: str, encoded, all_sets: List[SignatureSet],
        bucket: int
    ) -> Optional[List[bool]]:
        """Per-set verdicts for one pack (``None`` == every set valid),
        degrading through the tiers in the module docstring.  The
        caller made the breaker ``decision`` before the encode stage
        and holds the device lock for every decision EXCEPT "host" (an
        open breaker skips encode and lock alike — the short-circuit
        must not wait on a wedged chip).  Device *exceptions* never
        reach the waiters — only verdicts do; CancelledError always
        propagates (the caller releases an unresolved canary probe)."""
        m = self._metrics
        if decision == "host":
            # breaker open: don't pay the device timeout at all
            if m:
                m.breaker_short_circuits.inc()
            self._note_tier(brk.TIER_HOST)
            return await loop.run_in_executor(
                None, self._host_verify_pack, all_sets
            )
        if decision == "canary" and m:
            m.breaker_probes.inc()

        # tiers 1+2: batch kernel, one retry on a device fault (a canary
        # gets no retry — its job is to answer "is the device back?"
        # cheaply, and a second failing dispatch answers nothing new)
        attempts = 1 if decision == "canary" else 2
        batch_ok: Optional[bool] = None
        for attempt in range(attempts):
            if attempt:
                self._note_tier(brk.TIER_DEVICE_RETRY)
            try:
                batch_ok = await loop.run_in_executor(
                    None, self._execute_device, encoded
                )
                break
            except Exception as e:
                self._on_device_fault("execute_batch", attempt, e)
        if batch_ok is not None:
            if batch_ok:
                self._device_recovered(probe=decision == "canary")
                return None
            # batch verdict False — NOT a fault: split good from bad
            if m:
                m.batch_retries.inc()
        elif decision == "canary":
            # failed canary: breaker re-opens; settle the pack on host
            self._record_breaker_failure(probe=True)
            self._note_tier(brk.TIER_HOST)
            return await loop.run_in_executor(
                None, self._host_verify_pack, all_sets
            )

        # tier 3: vmapped per-set kernel (also the verdict-split path)
        try:
            per_set = await loop.run_in_executor(
                None, self._each_device, all_sets, bucket
            )
            if batch_ok is None:
                # the batch kernel faulted but per-set answered: the
                # device works — count the tier, clear the fault streak
                self._note_tier(brk.TIER_PER_SET)
            self._device_recovered(probe=decision == "canary")
            return per_set
        except Exception as e:
            self._on_device_fault("verify_each", attempts, e)

        # tier 4: the host oracle — correct verdicts, no device.  Only
        # a job where NO device dispatch succeeded counts against the
        # breaker: a working batch kernel whose per-set split faulted
        # is a partial fault, and tripping on it would evict a device
        # that demonstrably still answers the steady-state kernel.
        if batch_ok is None:
            self._record_breaker_failure(probe=decision == "canary")
        else:
            # the batch kernel answered (the steady-state path works):
            # for breaker purposes the device is healthy — this also
            # resolves a canary probe that got here via a verdict split
            self._device_recovered(probe=decision == "canary")
        self._note_tier(brk.TIER_HOST)
        return await loop.run_in_executor(None, self._host_verify_pack, all_sets)

    def _on_device_fault(self, stage: str, attempt: int, err: Exception) -> None:
        if self._metrics:
            self._metrics.device_faults.inc()
        self._log.warn(
            f"device {stage} fault (attempt {attempt + 1}): "
            f"{type(err).__name__}: {err} — degrading"
        )

    def _device_recovered(self, probe: bool = False) -> None:
        self._breaker.record_success(probe=probe)
        self._publish_breaker()

    def _record_breaker_failure(self, probe: bool = False) -> None:
        """One JOB whose device dispatches all faulted = one breaker
        failure (consecutive failed jobs trip it, not attempts);
        ``probe`` marks the canary's own outcome (only it may drive
        half-open transitions)."""
        tripped = self._breaker.record_failure(probe=probe)
        if tripped:
            if self._metrics:
                self._metrics.breaker_trips.inc()
            self._log.error(
                "device circuit breaker OPEN: routing verification to "
                "the host verifier until a canary probe succeeds"
            )
        self._publish_breaker()

    def _note_tier(self, tier: str) -> None:
        """Count one job engaging a degraded tier (metrics + the
        process-wide worst-tier record bench.py stamps into its JSON)."""
        brk.note_tier(tier)
        if self._metrics and tier != brk.TIER_DEVICE:
            self._metrics.degraded_jobs.labels(tier=tier).inc()

    def _publish_breaker(self) -> None:
        state = self._breaker.state
        if self._metrics:
            self._metrics.breaker_state.set(brk.STATE_CODES[state])
        brk.note_breaker(state, self._breaker.trips)


def _make_job(sets: List[SignatureSet]) -> _BufferedJob:
    loop = asyncio.get_running_loop()
    return _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
