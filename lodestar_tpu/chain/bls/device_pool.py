"""Device BLS verifier pool — TPU replacement for the worker-thread pool.

Reference semantics (packages/beacon-node/src/chain/bls/multithread/):
  * batchable sets buffer up to MAX_BUFFERED_SIGS=32 or MAX_BUFFER_WAIT_MS=
    100 ms, whichever first (index.ts:48,57)
  * at most MAX_SIGNATURE_SETS_PER_JOB=128 sets per device job (index.ts:39)
  * a failed batch falls back to per-set verification — here a single
    vmapped kernel instead of the worker's serial loop (worker.ts:76-98)
  * non-batchable requests dispatch immediately

The "pool" is the device itself: jobs run one at a time on the chip via an
asyncio lock (XLA serializes kernels anyway), with the batching window
amortizing dispatch + padded-bucket compile reuse (16/32/64/128).
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass, field
from typing import List, Optional, Sequence

from lodestar_tpu.crypto.bls.api import SignatureSet, verify_signature_set
from lodestar_tpu.utils import gather_settled
from .interface import VerifyOptions
from .metrics import BlsPoolMetrics

# The reference's per-worker cap is 128 sets/job (index.ts:39) — the
# right shape for a CPU thread.  The TPU kernel's batch latency is
# dominated by a ~350 ms sequential-scan floor and grows only mildly
# with width (measured r4: 628 ms at B=1024, ~1 s at 4096), so the
# device wants MUCH larger, LOAD-ADAPTIVE jobs: dispatch is work-
# conserving (one job in flight; when the device frees, the whole
# backlog becomes the next job, up to the cap).  Job width then
# self-regulates to arrival rate x job time — ~500 sets at the
# BASELINE per-slot firehose — while the cap bounds worst-case job
# latency.  The reference-mirror constant is kept for comparison.
REFERENCE_SETS_PER_JOB = 128
MAX_SIGNATURE_SETS_PER_JOB = 2048
MAX_BUFFER_WAIT_MS = 100

# Latency governor (VERDICT r4 #3: cap job width so kernel latency stays
# inside the gossip budget).  The kernel latency model t(B) = FLOOR +
# PER_SET*B is the r4 builder-session fit (628 ms @1024, ~1 s @4096 —
# re-fit from the next driver-visible bench).  A request's worst case is
# waiting out the in-flight job plus its own, so steady-state width is
# capped where t(width) <= budget/2; when the backlog exceeds the cap
# the pool is in overload — every extra request would miss the budget
# anyway, so it reverts to max-width jobs (throughput-optimal drain).
LATENCY_BUDGET_S = 1.0
MODEL_FLOOR_S = 0.35
MODEL_PER_SET_S = 0.00017
MIN_JOB_WIDTH = 128


@dataclass
class _BufferedJob:
    sets: List[SignatureSet]
    future: "asyncio.Future[bool]"
    added_at: float


class DeviceBlsVerifier:
    """Batched device verification behind the IBlsVerifier boundary."""

    def __init__(
        self,
        metrics: Optional[BlsPoolMetrics] = None,
        _backend=None,
        max_sets_per_job: int = MAX_SIGNATURE_SETS_PER_JOB,
    ):
        # _backend injection point for tests (defaults to the jit kernels)
        if _backend is None:
            from lodestar_tpu.ops.bls12_381 import verify as dv

            _backend = dv
        self._dv = _backend
        self._max_sets_per_job = max_sets_per_job
        self._buffer: List[_BufferedJob] = []
        self._buffer_sigs = 0
        self._flush_handle: Optional[asyncio.TimerHandle] = None
        self._inflight = False
        self._device_lock = asyncio.Lock()
        self._metrics = metrics
        self._closed = False
        # strong refs: the event loop only weakly references tasks, and a
        # GC'd job task would strand its waiters forever
        self._tasks: set = set()

    # ------------------------------------------------------------------

    async def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: VerifyOptions = VerifyOptions()
    ) -> bool:
        if self._closed:
            raise RuntimeError("verifier closed")
        if not sets:
            return False
        if opts.verify_on_main_thread:
            return all(verify_signature_set(s) for s in sets)

        if opts.batchable and len(sets) <= self._max_sets_per_job:
            # a single wide request would bypass the latency governor
            # (a buffered job is never split at flush time), so chunk it
            # to the governed width HERE and AND the chunk results
            cap = self._steady_width_cap()
            if len(sets) <= cap:
                return await self._enqueue(list(sets))
            chunks = [list(sets[i : i + cap]) for i in range(0, len(sets), cap)]
            # settle every chunk before reporting, so a failing chunk
            # can't leave detached siblings with unretrieved exceptions
            # (ADVICE r5)
            return all(
                await gather_settled(*(self._enqueue(c) for c in chunks))
            )

        # non-batchable or oversized: dispatch now, chunked to the
        # governed width.  All jobs serialize on the device, so a
        # max-width immediate job would hold queued-path bystanders past
        # the budget the governor guarantees (worst case = in-flight +
        # own job, each <= budget/2).  The oversized caller pays the
        # per-chunk dispatch floor — that is the accepted price of the
        # bystander guarantee.
        cap = self._steady_width_cap()
        results = []
        for i in range(0, len(sets), cap):
            chunk = list(sets[i : i + cap])
            results.append(await self._run_job([_make_job(chunk)]))
        return all(results)

    async def close(self) -> None:
        self._closed = True
        if self._flush_handle:
            self._flush_handle.cancel()
            self._flush_handle = None
        for job in self._buffer:
            if not job.future.done():
                job.future.set_exception(RuntimeError("verifier closed"))
        self._buffer.clear()
        self._buffer_sigs = 0

    # ------------------------------------------------------------------

    async def _enqueue(self, sets: List[SignatureSet]) -> bool:
        loop = asyncio.get_running_loop()
        job = _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
        self._buffer.append(job)
        self._buffer_sigs += len(sets)
        if self._metrics:
            self._metrics.job_queue_length.set(self._buffer_sigs)
        # Latency-bounded flush: dispatch immediately once a full device
        # job is buffered, otherwise wait up to MAX_BUFFER_WAIT_MS for
        # more sets (amortizing the kernel's fixed sequential-scan cost
        # over the widest batch the window collects).  The reference
        # flushes at 32 sigs (index.ts:48) because its workers saturate
        # early; the device's throughput grows with width instead.
        if self._buffer_sigs >= self._latency_width_cap():
            self._schedule_flush(0)
        elif self._flush_handle is None:
            self._schedule_flush(MAX_BUFFER_WAIT_MS / 1000)
        return await job.future

    def _steady_width_cap(self) -> int:
        """Width where t(width) <= LATENCY_BUDGET_S/2 under the fitted
        latency model (worst case = in-flight job + own job)."""
        budget_width = int(
            (LATENCY_BUDGET_S / 2 - MODEL_FLOOR_S) / MODEL_PER_SET_S
        )
        # MIN_JOB_WIDTH floors the MODEL-derived width (a degenerate fit
        # must not trickle tiny jobs) but never overrides an explicitly
        # smaller pool cap (tests construct 8-set pools)
        return min(self._max_sets_per_job, max(MIN_JOB_WIDTH, budget_width))

    def _latency_width_cap(self) -> int:
        """Steady-state governed width — unless the backlog already
        exceeds what capped jobs can clear in-budget, which is overload:
        revert to max-width drain (throughput-optimal).  The threshold
        is at least one full max job so a single wide request's chunks
        (just gathered by verify_signature_sets) cannot flip the pool
        into overload and re-fuse themselves into one over-budget job."""
        cap = self._steady_width_cap()
        # threshold: a full max-size request's chunks PLUS a capped job's
        # worth of bystanders must not count as overload (else the just-
        # chunked request re-fuses into one over-budget job)
        if self._buffer_sigs > self._max_sets_per_job + cap:
            return self._max_sets_per_job
        return cap

    def _schedule_flush(self, delay: float) -> None:
        loop = asyncio.get_running_loop()
        if self._flush_handle is not None:
            self._flush_handle.cancel()
        self._flush_handle = loop.call_later(delay, self._flush)

    def _flush(self) -> None:
        """Work-conserving dispatch: take ONE pack (the whole backlog,
        up to the job cap) and run it; remaining requests stay buffered
        and become the next job the moment the device frees.  Under
        load the job width adapts to arrival_rate x job_time instead of
        trickling fixed-size jobs through the window."""
        self._flush_handle = None
        if not self._buffer or self._inflight:
            return
        width_cap = self._latency_width_cap()
        pack: List[_BufferedJob] = []
        count = 0
        while self._buffer:
            job = self._buffer[0]
            if pack and count + len(job.sets) > width_cap:
                break
            pack.append(self._buffer.pop(0))
            count += len(job.sets)
        self._buffer_sigs -= count
        if self._metrics:
            self._metrics.job_queue_length.set(self._buffer_sigs)
        self._inflight = True
        task = asyncio.ensure_future(self._run_pack(pack))
        self._tasks.add(task)
        task.add_done_callback(self._tasks.discard)

    async def _run_pack(self, pack: List[_BufferedJob]) -> None:
        try:
            await self._run_job(pack)
        except Exception as e:  # propagate to waiters
            for job in pack:
                if not job.future.done():
                    job.future.set_exception(e)
        finally:
            self._inflight = False
            if self._buffer and not self._closed:
                self._schedule_flush(0)

    async def _run_job(self, pack: List[_BufferedJob]) -> bool:
        """Run one device job for a pack of requests; resolves each
        request's future.  Returns the AND of all results (for the
        immediate-dispatch path)."""
        all_sets: List[SignatureSet] = []
        for job in pack:
            all_sets.extend(job.sets)
        now = time.monotonic()
        if self._metrics:
            self._metrics.jobs_started.inc()
            self._metrics.sig_sets_total.inc(len(all_sets))
            for job in pack:
                self._metrics.job_wait_time.observe(now - job.added_at)

        loop = asyncio.get_running_loop()
        t0 = time.monotonic()
        async with self._device_lock:
            batch_ok = await loop.run_in_executor(
                None, self._dv.verify_signature_sets_device, all_sets
            )
            if batch_ok:
                per_set: Optional[List[bool]] = None
            else:
                # batch failed: one vmapped per-set pass splits good from bad
                if self._metrics:
                    self._metrics.batch_retries.inc()
                per_set = await loop.run_in_executor(
                    None, self._dv.verify_each_device, all_sets
                )
        if self._metrics:
            self._metrics.job_run_time.observe(time.monotonic() - t0)

        # resolve each buffered request
        ok_all = True
        offset = 0
        for job in pack:
            n = len(job.sets)
            if per_set is None:
                ok = True
            else:
                ok = all(per_set[offset : offset + n])
            offset += n
            if self._metrics and not ok:
                self._metrics.invalid_sets.inc()
            if not job.future.done():
                job.future.set_result(ok)
            ok_all = ok_all and ok
        return ok_all


def _make_job(sets: List[SignatureSet]) -> _BufferedJob:
    loop = asyncio.get_running_loop()
    return _BufferedJob(sets=sets, future=loop.create_future(), added_at=time.monotonic())
