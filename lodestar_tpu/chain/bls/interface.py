"""BLS verifier plugin boundary — the rebuild's IBlsVerifier.

Reference: packages/beacon-node/src/chain/bls/interface.ts:20.  The chain
talks only to this interface; implementations are the host-oracle verifier
(singleThread.ts role) and the TPU device pool (multithread/index.ts:98
role, with the worker pool replaced by batched device kernels).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Protocol, Sequence

from lodestar_tpu.crypto.bls.api import SignatureSet


@dataclass(frozen=True)
class VerifyOptions:
    """verifySignatureSets opts (interface.ts:30-46)."""

    # Aggregate this set with other sets in a batch-verification window.
    # Only safe when the caller tolerates batch-failure retry latency
    # (gossip objects); block sets use batchable=True too, via chunking.
    batchable: bool = False
    # Bypass the device/pool and verify on the host immediately.
    verify_on_main_thread: bool = False


class BlsVerifier(Protocol):
    async def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: VerifyOptions = VerifyOptions()
    ) -> bool:
        """True iff EVERY set verifies."""
        ...

    async def close(self) -> None:
        ...
