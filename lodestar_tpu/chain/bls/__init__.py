from .interface import BlsVerifier, VerifyOptions  # noqa: F401
from .single_thread import SingleThreadBlsVerifier  # noqa: F401
from .device_pool import (  # noqa: F401
    DeviceBlsVerifier,
    MAX_BUFFER_WAIT_MS,
    MAX_SIGNATURE_SETS_PER_JOB,
    REFERENCE_SETS_PER_JOB,
)
