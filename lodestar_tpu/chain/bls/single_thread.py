"""Host (oracle) BLS verifier — the singleThread.ts role.

Used for tests, tiny dev chains, and as the CPU fallback when no device is
available (reference: packages/beacon-node/src/chain/bls/singleThread.ts).
"""
from __future__ import annotations

from typing import Sequence

from lodestar_tpu.crypto.bls.api import (
    SignatureSet,
    verify_multiple_signature_sets,
    verify_signature_set,
)
from .interface import VerifyOptions


class SingleThreadBlsVerifier:
    async def verify_signature_sets(
        self, sets: Sequence[SignatureSet], opts: VerifyOptions = VerifyOptions()
    ) -> bool:
        if not sets:
            return False
        if len(sets) == 1:
            return verify_signature_set(sets[0])
        # batch with retry-each-individually on failure (maybeBatch.ts:17)
        if verify_multiple_signature_sets(list(sets)):
            return True
        return all(verify_signature_set(s) for s in sets)

    async def close(self) -> None:
        return None
