"""Gossip object validation (reference:
packages/beacon-node/src/chain/validation/{attestation,aggregateAndProof,
block}.ts).  Spec gossip conditions; BLS checks go through the chain's
pluggable verifier with {batchable: True} so they ride the device batching
window (attestation.ts:141-142).
"""
from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import List, Optional

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    ATTESTATION_SUBNET_COUNT,
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_SELECTION_PROOF,
)
from lodestar_tpu.state_transition.block.phase0 import get_domain
from lodestar_tpu.state_transition.signature_sets import (
    get_indexed_attestation_signature_set,
)
from lodestar_tpu.state_transition.util.aggregator import (
    is_aggregator_from_committee_length,
)
from lodestar_tpu.state_transition.util.domain import compute_signing_root
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.types import ssz
from .bls import VerifyOptions

ATTESTATION_PROPAGATION_SLOT_RANGE = 32  # spec p2p constant


class GossipErrorCode(str, Enum):
    FUTURE_SLOT = "FUTURE_SLOT"
    PAST_SLOT = "PAST_SLOT"
    NOT_EXACTLY_ONE_BIT = "NOT_EXACTLY_ONE_AGGREGATION_BIT_SET"
    UNKNOWN_BEACON_BLOCK_ROOT = "UNKNOWN_OR_PREFINALIZED_BEACON_BLOCK_ROOT"
    INVALID_TARGET = "INVALID_TARGET"
    WRONG_SUBNET = "INVALID_SUBNET_ID"
    ATTESTER_ALREADY_SEEN = "ATTESTATION_ALREADY_KNOWN"
    AGGREGATOR_ALREADY_SEEN = "AGGREGATOR_ALREADY_KNOWN"
    INVALID_SIGNATURE = "INVALID_SIGNATURE"
    COMMITTEE_INDEX_OUT_OF_RANGE = "COMMITTEE_INDEX_OUT_OF_RANGE"
    BITS_LENGTH_MISMATCH = "WRONG_NUMBER_OF_AGGREGATION_BITS"
    NOT_AGGREGATOR = "INVALID_AGGREGATOR"
    PROPOSER_ALREADY_SEEN = "REPEAT_PROPOSAL"
    BLOCK_SLOT_MISMATCH = "INCORRECT_PROPOSER"


class GossipValidationError(Exception):
    def __init__(self, code: GossipErrorCode, message: str = ""):
        super().__init__(f"{code.value}: {message}")
        self.code = code


def get_attestation_verification_state(chain, target, beacon_block_root: bytes) -> object:
    """State whose shufflings match the attestation's TARGET checkpoint
    (reference getStateForAttestationVerification): the target checkpoint
    state, so attestations on a fork with a different shuffling are checked
    against that fork's committees, not the head's.

    DoS guard: the attacker-controlled target root must be a KNOWN block
    that is an ancestor of the (already-verified-known) attested head —
    otherwise an attacker could point target.root at any old resident
    state and force an unbounded process_slots replay per gossip message
    (the reference rejects with INVALID_TARGET before touching regen)."""
    t_root = bytes(target.root)
    t_hex = "0x" + t_root.hex()
    if not chain.fork_choice.has_block(t_hex):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "unknown target")
    head_hex = "0x" + bytes(beacon_block_root).hex()
    if not chain.fork_choice.is_descendant(t_hex, head_hex):
        raise GossipValidationError(
            GossipErrorCode.INVALID_TARGET, "head does not descend from target"
        )
    st = chain.get_checkpoint_state(target.epoch, t_root)
    if st is None:
        # validating against the head's (possibly different) shuffling
        # would falsely reject — reject retriably instead
        raise GossipValidationError(
            GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT, "target state unavailable"
        )
    return st


def compute_subnet_for_attestation(
    committees_per_slot: int, slot: int, committee_index: int
) -> int:
    slots_since_epoch_start = slot % _p.SLOTS_PER_EPOCH
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return (committees_since_epoch_start + committee_index) % ATTESTATION_SUBNET_COUNT


async def validate_gossip_attestation(
    chain, attestation: "ssz.phase0.Attestation", subnet: Optional[int] = None
) -> List[int]:
    """validateGossipAttestation (attestation.ts:15): cheap spec checks
    first, then the single signature set with batchable=True.  Returns the
    attesting indices (exactly one)."""
    data = attestation.data
    current_slot = chain.clock.current_slot

    if data.slot > current_slot:
        raise GossipValidationError(GossipErrorCode.FUTURE_SLOT, f"slot {data.slot}")
    if data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE < current_slot:
        raise GossipValidationError(GossipErrorCode.PAST_SLOT, f"slot {data.slot}")
    if data.target.epoch != compute_epoch_at_slot(data.slot):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "target/slot")

    bits = list(attestation.aggregation_bits)
    if sum(bits) != 1:
        raise GossipValidationError(GossipErrorCode.NOT_EXACTLY_ONE_BIT)

    head_root = "0x" + bytes(data.beacon_block_root).hex()
    if not chain.fork_choice.has_block(head_root):
        raise GossipValidationError(
            GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT, head_root
        )

    state = get_attestation_verification_state(
        chain, data.target, bytes(data.beacon_block_root)
    )
    epoch_ctx = state.epoch_ctx
    try:
        committees_per_slot = epoch_ctx.get_committee_count_per_slot(data.target.epoch)
    except ValueError:
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "epoch not cached")
    if data.index >= committees_per_slot:
        raise GossipValidationError(GossipErrorCode.COMMITTEE_INDEX_OUT_OF_RANGE)
    if subnet is not None:
        expected = compute_subnet_for_attestation(
            committees_per_slot, data.slot, data.index
        )
        if subnet != expected:
            raise GossipValidationError(GossipErrorCode.WRONG_SUBNET, f"{subnet}!={expected}")

    committee = epoch_ctx.get_committee(data.slot, data.index)
    if len(bits) != len(committee):
        raise GossipValidationError(GossipErrorCode.BITS_LENGTH_MISMATCH)
    attester_index = int(committee[bits.index(True)])

    if chain.seen_attesters.is_known(data.target.epoch, attester_index):
        raise GossipValidationError(
            GossipErrorCode.ATTESTER_ALREADY_SEEN, str(attester_index)
        )

    indexed = ssz.phase0.IndexedAttestation(
        attesting_indices=[attester_index],
        data=data,
        signature=attestation.signature,
    )
    sig_set = get_indexed_attestation_signature_set(chain.cfg, state.state, indexed)
    if not await chain.bls.verify_signature_sets(
        [sig_set], VerifyOptions(batchable=True)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)

    chain.seen_attesters.add(data.target.epoch, attester_index)
    return [attester_index]


async def validate_gossip_aggregate_and_proof(
    chain, signed_agg: "ssz.altair.SignedContributionAndProof | ssz.phase0.SignedAggregateAndProof"
) -> List[int]:
    """validateGossipAggregateAndProof (aggregateAndProof.ts): all three
    signatures (selection proof, aggregator, aggregate) verified as ONE
    batchable job (aggregateAndProof.ts:125-130)."""
    agg_and_proof = signed_agg.message
    aggregate = agg_and_proof.aggregate
    data = aggregate.data
    current_slot = chain.clock.current_slot

    if data.slot > current_slot:
        raise GossipValidationError(GossipErrorCode.FUTURE_SLOT)
    if data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE < current_slot:
        raise GossipValidationError(GossipErrorCode.PAST_SLOT)
    if data.target.epoch != compute_epoch_at_slot(data.slot):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET)

    head_root = "0x" + bytes(data.beacon_block_root).hex()
    if not chain.fork_choice.has_block(head_root):
        raise GossipValidationError(GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT)

    data_root = ssz.phase0.AttestationData.hash_tree_root(data)
    if chain.seen_aggregated_attestations.is_known_superset(
        data.target.epoch, data_root, list(aggregate.aggregation_bits)
    ):
        raise GossipValidationError(GossipErrorCode.ATTESTER_ALREADY_SEEN, "superset")
    if chain.seen_aggregators.is_known(
        data.target.epoch, agg_and_proof.aggregator_index
    ):
        raise GossipValidationError(GossipErrorCode.AGGREGATOR_ALREADY_SEEN)

    state = get_attestation_verification_state(
        chain, data.target, bytes(data.beacon_block_root)
    )
    epoch_ctx = state.epoch_ctx
    committee = epoch_ctx.get_committee(data.slot, data.index)
    bits = list(aggregate.aggregation_bits)
    if len(bits) != len(committee):
        raise GossipValidationError(GossipErrorCode.BITS_LENGTH_MISMATCH)
    if not is_aggregator_from_committee_length(
        len(committee), bytes(agg_and_proof.selection_proof)
    ):
        raise GossipValidationError(GossipErrorCode.NOT_AGGREGATOR)
    if agg_and_proof.aggregator_index not in [int(c) for c in committee]:
        raise GossipValidationError(GossipErrorCode.NOT_AGGREGATOR, "not in committee")

    st = state.state
    aggregator_pk = bls.PublicKey.from_bytes(
        bytes(st.validators[agg_and_proof.aggregator_index].pubkey)
    )
    # 1. selection proof over the slot
    sel_domain = get_domain(chain.cfg, st, DOMAIN_SELECTION_PROOF, data.target.epoch)
    sel_root = compute_signing_root(ssz.phase0.Slot, data.slot, sel_domain)
    sel_set = bls.SignatureSet(
        aggregator_pk, sel_root,
        bls.Signature.from_bytes(bytes(agg_and_proof.selection_proof)),
    )
    # 2. aggregator signature over the AggregateAndProof
    agg_domain = get_domain(
        chain.cfg, st, DOMAIN_AGGREGATE_AND_PROOF, data.target.epoch
    )
    agg_root = compute_signing_root(
        ssz.phase0.AggregateAndProof, agg_and_proof, agg_domain
    )
    agg_set = bls.SignatureSet(
        aggregator_pk, agg_root,
        bls.Signature.from_bytes(bytes(signed_agg.signature)),
    )
    # 3. the aggregate attestation itself
    indices = [int(committee[i]) for i, b in enumerate(bits) if b]
    indexed = ssz.phase0.IndexedAttestation(
        attesting_indices=sorted(indices), data=data, signature=aggregate.signature
    )
    att_set = get_indexed_attestation_signature_set(chain.cfg, st, indexed)

    ok = await chain.bls.verify_signature_sets(
        [sel_set, agg_set, att_set], VerifyOptions(batchable=True)
    )
    if not ok:
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)

    chain.seen_aggregators.add(data.target.epoch, agg_and_proof.aggregator_index)
    chain.seen_aggregated_attestations.add(data.target.epoch, data_root, bits)
    return indices


async def validate_gossip_block(chain, signed_block) -> None:
    """validateGossipBlock (block.ts): slot/proposer/parent checks + the
    proposer signature (verified on its own, not batchable — blocks gate
    further processing)."""
    block = signed_block.message
    current_slot = chain.clock.current_slot
    if block.slot > current_slot:
        raise GossipValidationError(GossipErrorCode.FUTURE_SLOT, f"{block.slot}")
    fin = chain.fork_choice.store.finalized
    if block.slot <= fin.epoch * _p.SLOTS_PER_EPOCH:
        raise GossipValidationError(GossipErrorCode.PAST_SLOT, "pre-finalized")
    if chain.seen_block_proposers.is_known(block.slot, block.proposer_index):
        raise GossipValidationError(GossipErrorCode.PROPOSER_ALREADY_SEEN)
    parent_root = "0x" + bytes(block.parent_root).hex()
    if not chain.fork_choice.has_block(parent_root):
        raise GossipValidationError(GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT, "parent")

    # Dial the parent's state forward to the block's slot so the proposer
    # check ALWAYS runs — the head state's cached epoch lags at the first
    # slots of a new epoch and gossip must still reject wrong proposers.
    state = chain.regen.get_pre_state(bytes(block.parent_root), block.slot)
    expected = state.epoch_ctx.get_beacon_proposer(block.slot)
    if block.proposer_index != expected:
        raise GossipValidationError(GossipErrorCode.BLOCK_SLOT_MISMATCH)

    from lodestar_tpu.state_transition.signature_sets import (
        get_block_proposer_signature_set,
    )

    sig_set = get_block_proposer_signature_set(
        chain.cfg, state.state, state.epoch_ctx, signed_block
    )
    if not await chain.bls.verify_signature_sets([sig_set], VerifyOptions()):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)


# ---------------------------------------------------------------------------
# sync committee gossip (altair; reference chain/validation/syncCommittee.ts
# and syncCommitteeContributionAndProof.ts)
# ---------------------------------------------------------------------------


def _sync_committee_positions(state, validator_index: int):
    """All positions of a validator in the current sync committee."""
    pk = bytes(state.validators[validator_index].pubkey)
    return [
        i
        for i, cpk in enumerate(state.current_sync_committee.pubkeys)
        if bytes(cpk) == pk
    ]


async def validate_sync_committee_message(
    chain, message: "ssz.altair.SyncCommitteeMessage", subnet: int
) -> List[int]:
    """validateSyncCommitteeSigOnly + structural checks; returns the
    validator's positions within `subnet`'s subcommittee."""
    from lodestar_tpu.params import (
        DOMAIN_SYNC_COMMITTEE,
        SYNC_COMMITTEE_SUBNET_COUNT,
        SYNC_COMMITTEE_SUBNET_SIZE,
    )

    current_slot = chain.clock.current_slot
    if message.slot not in (current_slot, current_slot - 1):  # 1-slot clock disparity
        code = (
            GossipErrorCode.FUTURE_SLOT
            if message.slot > current_slot
            else GossipErrorCode.PAST_SLOT
        )
        raise GossipValidationError(code, f"sync msg slot {message.slot}")

    state = chain.get_head_state()
    st = state.state
    if not hasattr(st, "current_sync_committee"):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "pre-altair")
    positions = _sync_committee_positions(st, message.validator_index)
    sub_positions = [
        p % SYNC_COMMITTEE_SUBNET_SIZE
        for p in positions
        if p // SYNC_COMMITTEE_SUBNET_SIZE == subnet
    ]
    if not sub_positions:
        raise GossipValidationError(
            GossipErrorCode.WRONG_SUBNET, "validator not in subcommittee"
        )
    if chain.seen_sync_committee_messages.is_known(
        message.slot, subnet, message.validator_index
    ):
        raise GossipValidationError(GossipErrorCode.ATTESTER_ALREADY_SEEN, "sync msg")

    domain = get_domain(
        chain.cfg, st, DOMAIN_SYNC_COMMITTEE, compute_epoch_at_slot(message.slot)
    )
    root = compute_signing_root(
        ssz.phase0.Root, bytes(message.beacon_block_root), domain
    )
    pk = bls.PublicKey.from_bytes(bytes(st.validators[message.validator_index].pubkey))
    sig_set = bls.SignatureSet(
        pk, root, bls.Signature.from_bytes(bytes(message.signature))
    )
    if not await chain.bls.verify_signature_sets(
        [sig_set], VerifyOptions(batchable=True)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)
    chain.seen_sync_committee_messages.add(message.slot, subnet, message.validator_index)
    return sub_positions


async def validate_sync_committee_contribution(
    chain, signed: "ssz.altair.SignedContributionAndProof"
) -> None:
    """validateSyncCommitteeGossipContributionAndProof: selection proof is
    an aggregator proof over (slot, subcommittee); three signatures checked
    as one batchable job like aggregate-and-proof."""
    from lodestar_tpu.params import (
        DOMAIN_CONTRIBUTION_AND_PROOF,
        DOMAIN_SYNC_COMMITTEE,
        DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF,
        SYNC_COMMITTEE_SUBNET_COUNT,
        SYNC_COMMITTEE_SUBNET_SIZE,
    )
    from lodestar_tpu.state_transition.util.aggregator import (
        is_sync_committee_aggregator,
    )

    cp = signed.message
    contribution = cp.contribution
    current_slot = chain.clock.current_slot
    if contribution.slot not in (current_slot, current_slot - 1):
        code = (
            GossipErrorCode.FUTURE_SLOT
            if contribution.slot > current_slot
            else GossipErrorCode.PAST_SLOT
        )
        raise GossipValidationError(code, "contribution slot")
    if contribution.subcommittee_index >= SYNC_COMMITTEE_SUBNET_COUNT:
        raise GossipValidationError(GossipErrorCode.COMMITTEE_INDEX_OUT_OF_RANGE)
    if not any(contribution.aggregation_bits):
        raise GossipValidationError(GossipErrorCode.NOT_EXACTLY_ONE_BIT, "empty")
    if not is_sync_committee_aggregator(bytes(cp.selection_proof)):
        raise GossipValidationError(GossipErrorCode.NOT_AGGREGATOR)
    if chain.seen_sync_contributions.is_known(
        contribution.slot, contribution.subcommittee_index, cp.aggregator_index
    ):
        raise GossipValidationError(GossipErrorCode.AGGREGATOR_ALREADY_SEEN)

    state = chain.get_head_state()
    st = state.state
    if not hasattr(st, "current_sync_committee"):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "pre-altair")
    positions = _sync_committee_positions(st, cp.aggregator_index)
    if not any(
        p // SYNC_COMMITTEE_SUBNET_SIZE == contribution.subcommittee_index
        for p in positions
    ):
        raise GossipValidationError(GossipErrorCode.NOT_AGGREGATOR, "not in subcommittee")

    epoch = compute_epoch_at_slot(contribution.slot)
    agg_pk = bls.PublicKey.from_bytes(bytes(st.validators[cp.aggregator_index].pubkey))
    # 1. selection proof over SyncAggregatorSelectionData
    sel_data = ssz.altair.SyncAggregatorSelectionData(
        slot=contribution.slot, subcommittee_index=contribution.subcommittee_index
    )
    sel_domain = get_domain(
        chain.cfg, st, DOMAIN_SYNC_COMMITTEE_SELECTION_PROOF, epoch
    )
    sel_set = bls.SignatureSet(
        agg_pk,
        compute_signing_root(
            ssz.altair.SyncAggregatorSelectionData, sel_data, sel_domain
        ),
        bls.Signature.from_bytes(bytes(cp.selection_proof)),
    )
    # 2. the ContributionAndProof envelope
    cap_domain = get_domain(chain.cfg, st, DOMAIN_CONTRIBUTION_AND_PROOF, epoch)
    cap_set = bls.SignatureSet(
        agg_pk,
        compute_signing_root(ssz.altair.ContributionAndProof, cp, cap_domain),
        bls.Signature.from_bytes(bytes(signed.signature)),
    )
    # 3. the contribution's aggregate signature by the participants
    base = contribution.subcommittee_index * SYNC_COMMITTEE_SUBNET_SIZE
    pks = [
        bls.PublicKey.from_bytes(bytes(st.current_sync_committee.pubkeys[base + i]))
        for i, b in enumerate(contribution.aggregation_bits)
        if b
    ]
    msg_domain = get_domain(chain.cfg, st, DOMAIN_SYNC_COMMITTEE, epoch)
    msg_root = compute_signing_root(
        ssz.phase0.Root, bytes(contribution.beacon_block_root), msg_domain
    )
    contrib_set = bls.SignatureSet(
        bls.aggregate_public_keys(pks),
        msg_root,
        bls.Signature.from_bytes(bytes(contribution.signature)),
    )
    ok = await chain.bls.verify_signature_sets(
        [sel_set, cap_set, contrib_set], VerifyOptions(batchable=True)
    )
    if not ok:
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)
    chain.seen_sync_contributions.add(
        contribution.slot, contribution.subcommittee_index, cp.aggregator_index
    )


# ---------------------------------------------------------------------------
# eip4844 blobs (reference chain/validation/blobsSidecar.ts role; spec
# eip4844 p2p-interface validate_blobs_sidecar)
# ---------------------------------------------------------------------------


def validate_blobs_sidecar(
    slot: int, beacon_block_root: bytes, expected_kzg_commitments, sidecar
) -> None:
    """Spec validate_blobs_sidecar: sidecar must belong to the block and
    its blobs must match the block's commitments via the aggregated proof."""
    from lodestar_tpu.crypto import kzg

    if sidecar.beacon_block_slot != slot:
        raise GossipValidationError(
            GossipErrorCode.BLOCK_SLOT_MISMATCH, "sidecar slot"
        )
    if bytes(sidecar.beacon_block_root) != bytes(beacon_block_root):
        raise GossipValidationError(
            GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT, "sidecar root"
        )
    blobs = [bytes(b) for b in sidecar.blobs]
    comms = [bytes(c) for c in expected_kzg_commitments]
    if len(blobs) != len(comms):
        raise GossipValidationError(
            GossipErrorCode.INVALID_SIGNATURE, "blob/commitment count"
        )
    if not kzg.verify_aggregate_kzg_proof(
        blobs, comms, bytes(sidecar.kzg_aggregated_proof)
    ):
        raise GossipValidationError(
            GossipErrorCode.INVALID_SIGNATURE, "kzg aggregate proof"
        )


async def validate_gossip_block_and_blobs_sidecar(chain, pair) -> None:
    """beacon_block_and_blobs_sidecar gossip: the block validates like a
    normal gossip block, then the sidecar must prove the block's
    blob_kzg_commitments."""
    signed_block = pair.beacon_block
    await validate_gossip_block(chain, signed_block)
    block = signed_block.message
    root = type(block).hash_tree_root(block)
    validate_blobs_sidecar(
        block.slot, root, list(block.body.blob_kzg_commitments), pair.blobs_sidecar
    )


# ---------------------------------------------------------------------------
# voluntary exit + slashings gossip (chain/validation/{voluntaryExit,
# attesterSlashing,proposerSlashing}.ts roles; also run on REST pool
# submission like the reference's api/impl/beacon/pool handlers)
# ---------------------------------------------------------------------------


async def validate_gossip_voluntary_exit(chain, signed_exit) -> None:
    """Non-mutating preconditions of process_voluntary_exit + signature
    through the batch verifier."""
    from lodestar_tpu.params import FAR_FUTURE_EPOCH
    from lodestar_tpu.state_transition.block.phase0 import is_active_validator
    from lodestar_tpu.state_transition.signature_sets import (
        get_voluntary_exit_signature_set,
    )
    from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot

    exit_ = signed_exit.message
    idx = int(exit_.validator_index)
    if idx in chain.op_pool.voluntary_exits:
        raise GossipValidationError(
            GossipErrorCode.ATTESTER_ALREADY_SEEN, "exit already known"
        )
    st = chain.get_head_state().state
    if idx >= len(st.validators):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "unknown validator")
    v = st.validators[idx]
    epoch = compute_epoch_at_slot(st.slot)
    if (
        not is_active_validator(v, epoch)
        or v.exit_epoch != FAR_FUTURE_EPOCH
        or epoch < exit_.epoch
        or epoch < v.activation_epoch + chain.cfg.SHARD_COMMITTEE_PERIOD
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "exit preconditions")
    sig_set = get_voluntary_exit_signature_set(chain.cfg, st, signed_exit)
    if not await chain.bls.verify_signature_sets(
        [sig_set], VerifyOptions(batchable=True)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)


async def validate_gossip_attester_slashing(chain, slashing) -> None:
    from lodestar_tpu.state_transition.block.phase0 import (
        is_slashable_attestation_data,
        is_slashable_validator,
    )
    from lodestar_tpu.state_transition.signature_sets import (
        get_attester_slashing_signature_sets,
    )
    from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot

    a1, a2 = slashing.attestation_1, slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "not slashable")
    st = chain.get_head_state().state
    epoch = compute_epoch_at_slot(st.slot)
    common = set(int(i) for i in a1.attesting_indices) & set(
        int(i) for i in a2.attesting_indices
    )
    if not any(
        i < len(st.validators) and is_slashable_validator(st.validators[i], epoch)
        for i in common
    ):
        raise GossipValidationError(
            GossipErrorCode.INVALID_TARGET, "no slashable validators"
        )
    sets = get_attester_slashing_signature_sets(chain.cfg, st, slashing)
    if not await chain.bls.verify_signature_sets(
        sets, VerifyOptions(batchable=True)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)


async def validate_gossip_proposer_slashing(chain, slashing) -> None:
    from lodestar_tpu.state_transition.block.phase0 import is_slashable_validator
    from lodestar_tpu.state_transition.signature_sets import (
        get_proposer_slashing_signature_sets,
    )
    from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot

    h1, h2 = slashing.signed_header_1.message, slashing.signed_header_2.message
    if (
        h1.slot != h2.slot
        or h1.proposer_index != h2.proposer_index
        or h1 == h2
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "bad headers")
    st = chain.get_head_state().state
    idx = int(h1.proposer_index)
    if idx >= len(st.validators) or not is_slashable_validator(
        st.validators[idx], compute_epoch_at_slot(st.slot)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "not slashable")
    sets = get_proposer_slashing_signature_sets(chain.cfg, st, slashing)
    if not await chain.bls.verify_signature_sets(
        sets, VerifyOptions(batchable=True)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)


# ---------------------------------------------------------------------------
# capella bls_to_execution_change gossip (chain/validation/
# blsToExecutionChange.ts role)
# ---------------------------------------------------------------------------


async def validate_gossip_bls_to_execution_change(chain, signed_change) -> None:
    from lodestar_tpu.state_transition.block.capella import (
        check_bls_to_execution_change_preconditions,
        get_bls_to_execution_change_signature_set,
    )

    change = signed_change.message
    # p2p IGNORE: only the first change per validator index propagates
    if chain.seen_bls_to_execution_changes.is_known(change.validator_index):
        raise GossipValidationError(
            GossipErrorCode.ATTESTER_ALREADY_SEEN, "change already seen"
        )
    st = chain.get_head_state().state
    try:
        # same preconditions as the STF (block/capella.py) — one source of truth
        check_bls_to_execution_change_preconditions(st, change)
    except ValueError as e:
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, str(e))
    sig_set = get_bls_to_execution_change_signature_set(chain.cfg, st, signed_change)
    if not await chain.bls.verify_signature_sets(
        [sig_set], VerifyOptions(batchable=True)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)
    chain.seen_bls_to_execution_changes.add(change.validator_index)
