"""Gossip object validation (reference:
packages/beacon-node/src/chain/validation/{attestation,aggregateAndProof,
block}.ts).  Spec gossip conditions; BLS checks go through the chain's
pluggable verifier with {batchable: True} so they ride the device batching
window (attestation.ts:141-142).
"""
from __future__ import annotations

from dataclasses import dataclass
from enum import Enum
from typing import List, Optional

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    ATTESTATION_SUBNET_COUNT,
    DOMAIN_AGGREGATE_AND_PROOF,
    DOMAIN_SELECTION_PROOF,
)
from lodestar_tpu.state_transition.block.phase0 import get_domain
from lodestar_tpu.state_transition.signature_sets import (
    get_indexed_attestation_signature_set,
)
from lodestar_tpu.state_transition.util.aggregator import (
    is_aggregator_from_committee_length,
)
from lodestar_tpu.state_transition.util.domain import compute_signing_root
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.types import ssz
from .bls import VerifyOptions

ATTESTATION_PROPAGATION_SLOT_RANGE = 32  # spec p2p constant


class GossipErrorCode(str, Enum):
    FUTURE_SLOT = "FUTURE_SLOT"
    PAST_SLOT = "PAST_SLOT"
    NOT_EXACTLY_ONE_BIT = "NOT_EXACTLY_ONE_AGGREGATION_BIT_SET"
    UNKNOWN_BEACON_BLOCK_ROOT = "UNKNOWN_OR_PREFINALIZED_BEACON_BLOCK_ROOT"
    INVALID_TARGET = "INVALID_TARGET"
    WRONG_SUBNET = "INVALID_SUBNET_ID"
    ATTESTER_ALREADY_SEEN = "ATTESTATION_ALREADY_KNOWN"
    AGGREGATOR_ALREADY_SEEN = "AGGREGATOR_ALREADY_KNOWN"
    INVALID_SIGNATURE = "INVALID_SIGNATURE"
    COMMITTEE_INDEX_OUT_OF_RANGE = "COMMITTEE_INDEX_OUT_OF_RANGE"
    BITS_LENGTH_MISMATCH = "WRONG_NUMBER_OF_AGGREGATION_BITS"
    NOT_AGGREGATOR = "INVALID_AGGREGATOR"
    PROPOSER_ALREADY_SEEN = "REPEAT_PROPOSAL"
    BLOCK_SLOT_MISMATCH = "INCORRECT_PROPOSER"


class GossipValidationError(Exception):
    def __init__(self, code: GossipErrorCode, message: str = ""):
        super().__init__(f"{code.value}: {message}")
        self.code = code


def compute_subnet_for_attestation(
    committees_per_slot: int, slot: int, committee_index: int
) -> int:
    slots_since_epoch_start = slot % _p.SLOTS_PER_EPOCH
    committees_since_epoch_start = committees_per_slot * slots_since_epoch_start
    return (committees_since_epoch_start + committee_index) % ATTESTATION_SUBNET_COUNT


async def validate_gossip_attestation(
    chain, attestation: "ssz.phase0.Attestation", subnet: Optional[int] = None
) -> List[int]:
    """validateGossipAttestation (attestation.ts:15): cheap spec checks
    first, then the single signature set with batchable=True.  Returns the
    attesting indices (exactly one)."""
    data = attestation.data
    current_slot = chain.clock.current_slot

    if data.slot > current_slot:
        raise GossipValidationError(GossipErrorCode.FUTURE_SLOT, f"slot {data.slot}")
    if data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE < current_slot:
        raise GossipValidationError(GossipErrorCode.PAST_SLOT, f"slot {data.slot}")
    if data.target.epoch != compute_epoch_at_slot(data.slot):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "target/slot")

    bits = list(attestation.aggregation_bits)
    if sum(bits) != 1:
        raise GossipValidationError(GossipErrorCode.NOT_EXACTLY_ONE_BIT)

    head_root = "0x" + bytes(data.beacon_block_root).hex()
    if not chain.fork_choice.has_block(head_root):
        raise GossipValidationError(
            GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT, head_root
        )

    state = chain.get_head_state()
    epoch_ctx = state.epoch_ctx
    try:
        committees_per_slot = epoch_ctx.get_committee_count_per_slot(data.target.epoch)
    except ValueError:
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET, "epoch not cached")
    if data.index >= committees_per_slot:
        raise GossipValidationError(GossipErrorCode.COMMITTEE_INDEX_OUT_OF_RANGE)
    if subnet is not None:
        expected = compute_subnet_for_attestation(
            committees_per_slot, data.slot, data.index
        )
        if subnet != expected:
            raise GossipValidationError(GossipErrorCode.WRONG_SUBNET, f"{subnet}!={expected}")

    committee = epoch_ctx.get_committee(data.slot, data.index)
    if len(bits) != len(committee):
        raise GossipValidationError(GossipErrorCode.BITS_LENGTH_MISMATCH)
    attester_index = int(committee[bits.index(True)])

    if chain.seen_attesters.is_known(data.target.epoch, attester_index):
        raise GossipValidationError(
            GossipErrorCode.ATTESTER_ALREADY_SEEN, str(attester_index)
        )

    indexed = ssz.phase0.IndexedAttestation(
        attesting_indices=[attester_index],
        data=data,
        signature=attestation.signature,
    )
    sig_set = get_indexed_attestation_signature_set(chain.cfg, state.state, indexed)
    if not await chain.bls.verify_signature_sets(
        [sig_set], VerifyOptions(batchable=True)
    ):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)

    chain.seen_attesters.add(data.target.epoch, attester_index)
    return [attester_index]


async def validate_gossip_aggregate_and_proof(
    chain, signed_agg: "ssz.altair.SignedContributionAndProof | ssz.phase0.SignedAggregateAndProof"
) -> List[int]:
    """validateGossipAggregateAndProof (aggregateAndProof.ts): all three
    signatures (selection proof, aggregator, aggregate) verified as ONE
    batchable job (aggregateAndProof.ts:125-130)."""
    agg_and_proof = signed_agg.message
    aggregate = agg_and_proof.aggregate
    data = aggregate.data
    current_slot = chain.clock.current_slot

    if data.slot > current_slot:
        raise GossipValidationError(GossipErrorCode.FUTURE_SLOT)
    if data.slot + ATTESTATION_PROPAGATION_SLOT_RANGE < current_slot:
        raise GossipValidationError(GossipErrorCode.PAST_SLOT)
    if data.target.epoch != compute_epoch_at_slot(data.slot):
        raise GossipValidationError(GossipErrorCode.INVALID_TARGET)

    head_root = "0x" + bytes(data.beacon_block_root).hex()
    if not chain.fork_choice.has_block(head_root):
        raise GossipValidationError(GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT)

    data_root = ssz.phase0.AttestationData.hash_tree_root(data)
    if chain.seen_aggregated_attestations.is_known_superset(
        data.target.epoch, data_root, list(aggregate.aggregation_bits)
    ):
        raise GossipValidationError(GossipErrorCode.ATTESTER_ALREADY_SEEN, "superset")
    if chain.seen_aggregators.is_known(
        data.target.epoch, agg_and_proof.aggregator_index
    ):
        raise GossipValidationError(GossipErrorCode.AGGREGATOR_ALREADY_SEEN)

    state = chain.get_head_state()
    epoch_ctx = state.epoch_ctx
    committee = epoch_ctx.get_committee(data.slot, data.index)
    bits = list(aggregate.aggregation_bits)
    if len(bits) != len(committee):
        raise GossipValidationError(GossipErrorCode.BITS_LENGTH_MISMATCH)
    if not is_aggregator_from_committee_length(
        len(committee), bytes(agg_and_proof.selection_proof)
    ):
        raise GossipValidationError(GossipErrorCode.NOT_AGGREGATOR)
    if agg_and_proof.aggregator_index not in [int(c) for c in committee]:
        raise GossipValidationError(GossipErrorCode.NOT_AGGREGATOR, "not in committee")

    st = state.state
    aggregator_pk = bls.PublicKey.from_bytes(
        bytes(st.validators[agg_and_proof.aggregator_index].pubkey)
    )
    # 1. selection proof over the slot
    sel_domain = get_domain(chain.cfg, st, DOMAIN_SELECTION_PROOF, data.target.epoch)
    sel_root = compute_signing_root(ssz.phase0.Slot, data.slot, sel_domain)
    sel_set = bls.SignatureSet(
        aggregator_pk, sel_root,
        bls.Signature.from_bytes(bytes(agg_and_proof.selection_proof)),
    )
    # 2. aggregator signature over the AggregateAndProof
    agg_domain = get_domain(
        chain.cfg, st, DOMAIN_AGGREGATE_AND_PROOF, data.target.epoch
    )
    agg_root = compute_signing_root(
        ssz.phase0.AggregateAndProof, agg_and_proof, agg_domain
    )
    agg_set = bls.SignatureSet(
        aggregator_pk, agg_root,
        bls.Signature.from_bytes(bytes(signed_agg.signature)),
    )
    # 3. the aggregate attestation itself
    indices = [int(committee[i]) for i, b in enumerate(bits) if b]
    indexed = ssz.phase0.IndexedAttestation(
        attesting_indices=sorted(indices), data=data, signature=aggregate.signature
    )
    att_set = get_indexed_attestation_signature_set(chain.cfg, st, indexed)

    ok = await chain.bls.verify_signature_sets(
        [sel_set, agg_set, att_set], VerifyOptions(batchable=True)
    )
    if not ok:
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)

    chain.seen_aggregators.add(data.target.epoch, agg_and_proof.aggregator_index)
    chain.seen_aggregated_attestations.add(data.target.epoch, data_root, bits)
    return indices


async def validate_gossip_block(chain, signed_block) -> None:
    """validateGossipBlock (block.ts): slot/proposer/parent checks + the
    proposer signature (verified on its own, not batchable — blocks gate
    further processing)."""
    block = signed_block.message
    current_slot = chain.clock.current_slot
    if block.slot > current_slot:
        raise GossipValidationError(GossipErrorCode.FUTURE_SLOT, f"{block.slot}")
    fin = chain.fork_choice.store.finalized
    if block.slot <= fin.epoch * _p.SLOTS_PER_EPOCH:
        raise GossipValidationError(GossipErrorCode.PAST_SLOT, "pre-finalized")
    if chain.seen_block_proposers.is_known(block.slot, block.proposer_index):
        raise GossipValidationError(GossipErrorCode.PROPOSER_ALREADY_SEEN)
    parent_root = "0x" + bytes(block.parent_root).hex()
    if not chain.fork_choice.has_block(parent_root):
        raise GossipValidationError(GossipErrorCode.UNKNOWN_BEACON_BLOCK_ROOT, "parent")

    state = chain.get_head_state()
    if compute_epoch_at_slot(block.slot) == state.epoch_ctx.epoch:
        expected = state.epoch_ctx.get_beacon_proposer(block.slot)
        if block.proposer_index != expected:
            raise GossipValidationError(GossipErrorCode.BLOCK_SLOT_MISMATCH)

    from lodestar_tpu.state_transition.signature_sets import (
        get_block_proposer_signature_set,
    )

    sig_set = get_block_proposer_signature_set(
        chain.cfg, state.state, state.epoch_ctx, signed_block
    )
    if not await chain.bls.verify_signature_sets([sig_set], VerifyOptions()):
        raise GossipValidationError(GossipErrorCode.INVALID_SIGNATURE)
