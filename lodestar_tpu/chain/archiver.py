"""Archiver: hot -> cold migration on finalization (reference:
packages/beacon-node/src/chain/archiver/ — archiveBlocks.ts,
archiveStates.ts).

On each finalized-checkpoint event the canonical chain up to the
finalized slot moves from the hot by-root block repo into the by-slot
block archive; non-canonical (pruned fork) blocks are dropped, the
finalized state is persisted to the state archive, and fork choice +
state caches are pruned.
"""
from __future__ import annotations

from typing import List, Optional

from lodestar_tpu.params import ACTIVE_PRESET as _p


class Archiver:
    def __init__(self, chain, states_per_archive_epochs: int = 1):
        from .chain import ChainEvent

        self.chain = chain
        self.states_per_archive_epochs = states_per_archive_epochs
        self._last_archived_slot = -1
        chain.on(ChainEvent.finalized, self.on_finalized)

    # ------------------------------------------------------------------

    def on_finalized(self, checkpoint) -> None:
        chain = self.chain
        db = chain.db
        fin_root = bytes.fromhex(checkpoint.root[2:])
        fin_block = db.block.get(fin_root)
        if fin_block is None:
            return
        fin_slot = fin_block.message.slot

        # walk the canonical chain backwards from the finalized block
        canonical: List[tuple] = []
        root = fin_root
        while True:
            signed = db.block.get(root)
            if signed is None:
                break
            slot = signed.message.slot
            if slot <= self._last_archived_slot:
                break
            canonical.append((slot, root, signed))
            parent = bytes(signed.message.parent_root)
            if parent == root or slot == 0:
                break
            root = parent

        # cold store: by-slot archive + root index (archiveBlocks.ts)
        for slot, root_, signed in reversed(canonical):
            db.block_archive.put(slot, signed)
            db.block_archive_root_index.put(root_, slot)

        # archive the finalized state if cached (archiveStates.ts)
        st = chain.state_cache.get(fin_root)
        if st is not None:
            db.state_archive.put(st.state.slot, st.state)
            db.state_archive_root_index.put(fin_root, st.state.slot)

        # prune fork choice and drop non-canonical hot blocks below the
        # finalized slot
        pruned = chain.fork_choice.prune(checkpoint.root)
        keep = {r for _, r, _ in canonical}
        for node in pruned:
            r = bytes.fromhex(node.block_root[2:])
            if r not in keep and r != fin_root:
                db.block.delete(r)

        self._last_archived_slot = fin_slot

    # queries (blockArchive consumers: byRange sync, API) ---------------

    def get_archived_block(self, slot: int):
        return self.chain.db.block_archive.get(slot)

    def get_archived_block_by_root(self, root: bytes):
        slot = self.chain.db.block_archive_root_index.get(root)
        if slot is None:
            return None
        return self.chain.db.block_archive.get(slot)
