"""In-process dev chain: interop validators producing and importing blocks.

The engine behind the `dev` command (reference: packages/cli/src/cmds/dev/
plus chain/produceBlock/produceBlockBody.ts in miniature): every slot the
scheduled interop validator proposes a block carrying the previous slot's
attestations, the block runs through the full state transition, and its
signature sets verify through the pluggable BLS verifier.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
)
from lodestar_tpu.state_transition import CachedBeaconState, process_slots, state_transition
from lodestar_tpu.state_transition.block.phase0 import get_domain
from lodestar_tpu.state_transition.signature_sets import get_block_signature_sets
from lodestar_tpu.state_transition.util.domain import compute_signing_root
from lodestar_tpu.state_transition.util.genesis import init_dev_state
from lodestar_tpu.state_transition.util.interop import interop_secret_keys
from lodestar_tpu.state_transition.util.misc import (
    compute_epoch_at_slot,
    compute_start_slot_at_epoch,
    get_block_root_at_slot,
)
from lodestar_tpu.types import ssz


@dataclass
class ImportedBlock:
    root: bytes
    block: "ssz.phase0.SignedBeaconBlock"
    post_state: CachedBeaconState


class DevChain:
    """Single-node in-memory chain of interop validators."""

    def __init__(self, cfg, validator_count: int, genesis_time: int = 0):
        self.cfg = cfg
        self.sks = interop_secret_keys(validator_count)
        _, state = init_dev_state(cfg, validator_count, genesis_time=genesis_time)
        self.head = CachedBeaconState(cfg, state)
        self.blocks: Dict[bytes, ImportedBlock] = {}
        self.pending_atts: List["ssz.phase0.Attestation"] = []
        self.verified_set_count = 0

    # ------------------------------------------------------------------

    def _head_root(self) -> bytes:
        """Root of the head block: the latest header with its state_root
        filled the way the next process_slot will fill it."""
        hdr = self.head.state.latest_block_header
        hdr = ssz.phase0.BeaconBlockHeader(
            slot=hdr.slot,
            proposer_index=hdr.proposer_index,
            parent_root=hdr.parent_root,
            state_root=hdr.state_root,
            body_root=hdr.body_root,
        )
        if bytes(hdr.state_root) == b"\x00" * 32:
            hdr.state_root = self.head.hash_tree_root()
        return ssz.phase0.BeaconBlockHeader.hash_tree_root(hdr)

    def attest(self, slot: int) -> List["ssz.phase0.Attestation"]:
        """All committees of `slot` attest to the current head (validator
        spec produce-attestation, simplified to full participation)."""
        state_at = self.head.clone()
        if state_at.state.slot < slot:
            process_slots(state_at, slot)
        st = state_at.state
        epoch = compute_epoch_at_slot(slot)
        head_root = self._head_root()
        start_slot = compute_start_slot_at_epoch(epoch)
        if start_slot == st.slot:
            target_root = head_root
        else:
            target_root = get_block_root_at_slot(st, start_slot)
        atts = []
        cps = state_at.epoch_ctx.get_committee_count_per_slot(epoch)
        for index in range(cps):
            committee = state_at.epoch_ctx.get_committee(slot, index)
            if len(committee) == 0:
                continue
            data = ssz.phase0.AttestationData(
                slot=slot,
                index=index,
                beacon_block_root=head_root,
                source=st.current_justified_checkpoint,
                target=ssz.phase0.Checkpoint(epoch=epoch, root=target_root),
            )
            domain = get_domain(self.cfg, st, DOMAIN_BEACON_ATTESTER, epoch)
            root = compute_signing_root(ssz.phase0.AttestationData, data, domain)
            sigs = [self.sks[int(v)].sign(root) for v in committee]
            atts.append(
                ssz.phase0.Attestation(
                    aggregation_bits=[True] * len(committee),
                    data=data,
                    signature=bls.aggregate_signatures(sigs).to_bytes(),
                )
            )
        self.pending_atts.extend(atts)
        return atts

    def produce_block(self, slot: int) -> "ssz.phase0.SignedBeaconBlock":
        pre = self.head.clone()
        process_slots(pre, slot)
        proposer = pre.epoch_ctx.get_beacon_proposer(slot)
        sk = self.sks[proposer]
        epoch = compute_epoch_at_slot(slot)

        randao_domain = get_domain(self.cfg, pre.state, DOMAIN_RANDAO, epoch)
        randao_reveal = sk.sign(
            compute_signing_root(ssz.phase0.Epoch, epoch, randao_domain)
        ).to_bytes()

        atts = [
            a
            for a in self.pending_atts
            if a.data.slot + _p.MIN_ATTESTATION_INCLUSION_DELAY <= slot <= a.data.slot + _p.SLOTS_PER_EPOCH
        ][: _p.MAX_ATTESTATIONS]

        from lodestar_tpu.types import fork_of_state, types_for

        fork = fork_of_state(pre.state)
        _, block_t, signed_t, body_t = types_for(fork)
        body = body_t(
            randao_reveal=randao_reveal,
            eth1_data=pre.state.eth1_data,
            graffiti=b"lodestar-tpu-dev".ljust(32, b"\x00"),
            attestations=atts,
        )
        if hasattr(body, "sync_aggregate"):
            body.sync_aggregate = self._make_sync_aggregate(pre, slot)
        if hasattr(body, "execution_payload"):
            from lodestar_tpu.state_transition.block.bellatrix import (
                is_merge_transition_complete,
            )

            if is_merge_transition_complete(pre.state):
                from lodestar_tpu.execution.engine import build_dev_payload

                body.execution_payload = build_dev_payload(self.cfg, pre.state)
        block = block_t(
            slot=slot,
            proposer_index=proposer,
            parent_root=self._head_root(),
            state_root=b"\x00" * 32,
            body=body,
        )
        # compute the post-state root (produceBlock/computeNewStateRoot.ts)
        trial = signed_t(message=block, signature=b"\x00" * 96)
        post = state_transition(
            self.head,
            trial,
            verify_state_root=False,
            verify_proposer=False,
            verify_signatures=False,
        )
        block.state_root = post.hash_tree_root()

        domain = get_domain(self.cfg, pre.state, DOMAIN_BEACON_PROPOSER, epoch)
        sig = sk.sign(
            compute_signing_root(block_t, block, domain)
        ).to_bytes()
        return signed_t(message=block, signature=sig)

    def _make_sync_aggregate(self, pre: CachedBeaconState, slot: int):
        """Full-participation SyncAggregate over the previous slot's block
        root, signed by the interop keys of the current sync committee."""
        from lodestar_tpu.params import DOMAIN_SYNC_COMMITTEE

        st = pre.state
        previous_slot = max(1, slot) - 1
        root = get_block_root_at_slot(st, previous_slot)
        domain = get_domain(
            self.cfg, st, DOMAIN_SYNC_COMMITTEE, compute_epoch_at_slot(previous_slot)
        )
        signing_root = compute_signing_root(ssz.phase0.Root, root, domain)
        indices = [
            pre.epoch_ctx.pubkey2index[bytes(pk)]
            for pk in st.current_sync_committee.pubkeys
        ]
        sigs = [self.sks[i].sign(signing_root) for i in indices]
        return ssz.altair.SyncAggregate(
            sync_committee_bits=[True] * _p.SYNC_COMMITTEE_SIZE,
            sync_committee_signature=bls.aggregate_signatures(sigs).to_bytes(),
        )

    def import_block(
        self, signed_block, verifier=None, verify_signatures: bool = True
    ) -> ImportedBlock:
        """Full import: STF + signature sets through the verifier (the
        3-way-parallel import pipeline collapsed to sequential host code;
        the async pipeline lives in chain/blocks.py)."""
        pre = self.head
        if verify_signatures:
            post = state_transition(
                pre, signed_block, verify_state_root=True,
                verify_proposer=False, verify_signatures=False,
            )
            sets = get_block_signature_sets(
                self.cfg, post.state, post.epoch_ctx, signed_block
            )
            if verifier is None:
                ok = bls.verify_multiple_signature_sets(sets)
            else:
                import asyncio

                ok = asyncio.run(verifier.verify_signature_sets(sets))
            if not ok:
                raise ValueError("block signature sets failed verification")
            self.verified_set_count += len(sets)
        else:
            post = state_transition(
                pre, signed_block, verify_state_root=True,
                verify_proposer=False, verify_signatures=False,
            )
        msg = signed_block.message
        root = type(msg).hash_tree_root(msg)
        imported = ImportedBlock(root=root, block=signed_block, post_state=post)
        self.blocks[root] = imported
        self.head = post
        consumed = {
            ssz.phase0.AttestationData.hash_tree_root(a.data)
            for a in signed_block.message.body.attestations
        }
        self.pending_atts = [
            a
            for a in self.pending_atts
            if ssz.phase0.AttestationData.hash_tree_root(a.data) not in consumed
        ]
        return imported

    # ------------------------------------------------------------------

    def run_slot(self, slot: int, verifier=None, verify_signatures: bool = True):
        """One full slot: attest at slot-1, propose+import at `slot`."""
        if slot > 1:
            self.attest(slot - 1)
        block = self.produce_block(slot)
        return self.import_block(block, verifier, verify_signatures)

    def run_until(self, slot: int, verifier=None, verify_signatures: bool = True):
        start = self.head.state.slot + 1
        for s in range(start, slot + 1):
            self.run_slot(s, verifier, verify_signatures)
        return self.head
