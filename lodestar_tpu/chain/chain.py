"""BeaconChain: the node's central composition (reference:
packages/beacon-node/src/chain/chain.ts:75 BeaconChain).

Wires the clock, fork choice, state caches/regen, op pools, seen caches,
the pluggable BLS verifier, the execution engine, and the block pipeline:

  process_block -> bounded queue -> verify (payload ∥ STF ∥ signatures,
  asyncio.gather mirroring verifyBlock.ts:71-80) -> import (db + fork
  choice + head update + pruning + events)
"""
from __future__ import annotations

import asyncio
import time
from dataclasses import dataclass
from enum import Enum
from typing import Awaitable, Callable, Dict, List, Optional

from lodestar_tpu.db import BeaconDb
from lodestar_tpu.params import ACTIVE_PRESET as _p, INTERVALS_PER_SLOT
from lodestar_tpu.state_transition import CachedBeaconState, state_transition
from lodestar_tpu.state_transition.epoch.phase0 import (
    before_process_epoch,
    weigh_justification_and_finalization,
)
from lodestar_tpu.state_transition.signature_sets import get_block_signature_sets
from lodestar_tpu.types import ssz
from lodestar_tpu.utils import gather_settled, get_logger
from lodestar_tpu.utils.queue import JobItemQueue, QueueType
from .bls import BlsVerifier, SingleThreadBlsVerifier, VerifyOptions
from .clock import LocalClock
from .op_pools import (
    AggregatedAttestationPool,
    AttestationPool,
    OpPool,
    SyncCommitteeMessagePool,
    SyncContributionAndProofPool,
)
from .regen import CheckpointStateCache, StateContextCache, StateRegenerator
from .seen_cache import (
    SeenBlsToExecutionChanges,
    SeenAggregatedAttestations,
    SeenAttesters,
    SeenBlockProposers,
    SeenSyncCommitteeMessages,
)
from lodestar_tpu.fork_choice import (
    CheckpointHex,
    ExecutionStatus,
    ForkChoice,
    ForkChoiceStore,
    ProtoArray,
    ProtoBlock,
)

_log = get_logger("chain")

BLOCK_QUEUE_LENGTH = 256  # blocks/index.ts:17


class ExecutionPayloadInvalidError(ValueError):
    """The EL rejected a block's execution payload (newPayload INVALID).
    Carries the EL's diagnostics: ``latest_valid_hash`` anchors the
    invalidation sweep, ``validation_error`` is the EL's own message."""

    def __init__(
        self,
        block_root: bytes,
        latest_valid_hash: Optional[bytes] = None,
        validation_error: Optional[str] = None,
    ):
        lvh = "0x" + latest_valid_hash.hex() if latest_valid_hash else None
        super().__init__(
            f"execution payload invalid for block 0x{block_root.hex()[:8]}: "
            f"latestValidHash={lvh} validationError={validation_error!r}"
        )
        self.block_root = block_root
        self.latest_valid_hash = latest_valid_hash
        self.validation_error = validation_error


class ChainEvent(str, Enum):
    block = "block"
    head = "head"
    justified = "justified"
    finalized = "finalized"
    checkpoint = "checkpoint"


def _hex(root: bytes) -> str:
    return "0x" + root.hex()


def compute_unrealized_checkpoints(cfg, cached: CachedBeaconState):
    """What justification/finalization WOULD be if the epoch ended now
    (reference computeUnrealizedCheckpoints, used for fork-choice
    viability).  Runs the flag sweep + a non-mutating weigh pass."""
    from lodestar_tpu.types import fork_of_state
    from lodestar_tpu.params import ForkName

    state = cached.state
    if fork_of_state(state) is ForkName.phase0:
        proc = before_process_epoch(cfg, state, cached.epoch_ctx)
        from lodestar_tpu.state_transition.epoch.phase0 import (
            FLAG_CURR_TARGET,
            FLAG_PREV_TARGET,
            _unslashed_attesting_balance,
        )

        prev_target = _unslashed_attesting_balance(proc, FLAG_PREV_TARGET)
        curr_target = _unslashed_attesting_balance(proc, FLAG_CURR_TARGET)
    else:
        from lodestar_tpu.params import TIMELY_TARGET_FLAG_INDEX
        from lodestar_tpu.state_transition.epoch.altair import (
            _unslashed_participating_balance,
            before_process_epoch as before_altair,
        )

        proc = before_altair(cfg, state, cached.epoch_ctx)
        prev_target = _unslashed_participating_balance(
            proc, TIMELY_TARGET_FLAG_INDEX, previous=True
        )
        curr_target = _unslashed_participating_balance(
            proc, TIMELY_TARGET_FLAG_INDEX, previous=False
        )
    if proc.current_epoch <= 1:
        return state.current_justified_checkpoint, state.finalized_checkpoint

    class _Shadow:
        __slots__ = (
            "slot", "previous_justified_checkpoint", "current_justified_checkpoint",
            "finalized_checkpoint", "justification_bits", "block_roots",
        )

    sh = _Shadow()
    sh.slot = state.slot
    sh.previous_justified_checkpoint = state.previous_justified_checkpoint
    sh.current_justified_checkpoint = state.current_justified_checkpoint
    sh.finalized_checkpoint = state.finalized_checkpoint
    sh.justification_bits = list(state.justification_bits)
    sh.block_roots = state.block_roots

    weigh_justification_and_finalization(
        cfg, sh, proc.total_active_balance, prev_target, curr_target
    )
    return sh.current_justified_checkpoint, sh.finalized_checkpoint


class BeaconChain:
    def __init__(
        self,
        cfg,
        db: BeaconDb,
        anchor_state,
        verifier: Optional[BlsVerifier] = None,
        execution_engine=None,
        clock: Optional[LocalClock] = None,
        metrics=None,
        eth1=None,
        merge_tracker=None,
    ):
        self.cfg = cfg
        self.db = db
        self.bls = verifier or SingleThreadBlsVerifier()
        self.execution_engine = execution_engine
        self.eth1 = eth1  # Eth1DepositDataTracker or None
        self.merge_tracker = merge_tracker  # Eth1MergeBlockTracker or None
        self.metrics = metrics  # lodestar_tpu.metrics.Metrics or None
        # True while the last engine call failed at transport level
        # (surfaced on /eth/v1/node/syncing as el_offline)
        self.el_offline = False
        from lodestar_tpu.config import ForkConfig

        # fork schedule lookups (engine version selection per head slot)
        self._fork_config = ForkConfig(cfg)
        anchor = CachedBeaconState(cfg, anchor_state)
        self.genesis_time = anchor_state.genesis_time
        self.genesis_validators_root = bytes(anchor_state.genesis_validators_root)
        self.clock = clock or LocalClock(self.genesis_time, cfg.SECONDS_PER_SLOT)

        # anchor block (genesis or checkpoint block header)
        hdr = anchor_state.latest_block_header
        anchor_hdr = ssz.phase0.BeaconBlockHeader(
            slot=hdr.slot, proposer_index=hdr.proposer_index,
            parent_root=hdr.parent_root, state_root=hdr.state_root,
            body_root=hdr.body_root,
        )
        if bytes(anchor_hdr.state_root) == b"\x00" * 32:
            anchor_hdr.state_root = anchor.hash_tree_root()
        anchor_root = ssz.phase0.BeaconBlockHeader.hash_tree_root(anchor_hdr)
        self.anchor_root = anchor_root

        # caches + regen
        self.state_cache = StateContextCache()
        self.checkpoint_state_cache = CheckpointStateCache()
        self.state_cache.add(anchor_root, anchor)
        self.state_cache.pin(anchor_root)  # regen's terminal ancestor
        self._pinned_finalized_root = anchor_root
        self.regen = StateRegenerator(
            self.state_cache,
            self.db.block.get,
            on_miss=(
                self.metrics.lodestar.regen_requests.inc if self.metrics else None
            ),
        )

        # fork choice
        fin = anchor_state.finalized_checkpoint
        just = anchor_state.current_justified_checkpoint
        anchor_epoch = anchor_state.slot // _p.SLOTS_PER_EPOCH
        anchor_cp = CheckpointHex(max(just.epoch, anchor_epoch), _hex(anchor_root))
        balances = list(anchor.epoch_ctx.effective_balance_increments)
        proto = ProtoArray.initialize(
            ProtoBlock(
                slot=anchor_state.slot,
                block_root=_hex(anchor_root),
                parent_root=_hex(bytes(hdr.parent_root)),
                state_root=_hex(bytes(anchor_hdr.state_root)),
                target_root=_hex(anchor_root),
                justified_epoch=anchor_cp.epoch,
                justified_root=anchor_cp.root,
                finalized_epoch=anchor_cp.epoch,
                finalized_root=anchor_cp.root,
                unrealized_justified_epoch=anchor_cp.epoch,
                unrealized_justified_root=anchor_cp.root,
                unrealized_finalized_epoch=anchor_cp.epoch,
                unrealized_finalized_root=anchor_cp.root,
                execution_status=ExecutionStatus.PreMerge,
            ),
            current_slot=max(anchor_state.slot, self.clock.current_slot),
        )
        store = ForkChoiceStore(
            current_slot=max(anchor_state.slot, self.clock.current_slot),
            justified=anchor_cp,
            justified_balances=balances,
            finalized=anchor_cp,
            unrealized_justified=anchor_cp,
            unrealized_finalized=anchor_cp,
        )
        self.fork_choice = ForkChoice(
            cfg, store, proto,
            justified_balances_getter=self._get_justified_balances,
        )

        # pools + dedup caches
        self.attestation_pool = AttestationPool()
        self.aggregated_attestation_pool = AggregatedAttestationPool()
        self.sync_committee_message_pool = SyncCommitteeMessagePool()
        self.sync_contribution_pool = SyncContributionAndProofPool()
        self.op_pool = OpPool()
        self.seen_attesters = SeenAttesters()
        self.seen_aggregators = SeenAttesters()
        self.seen_aggregated_attestations = SeenAggregatedAttestations()
        self.seen_block_proposers = SeenBlockProposers()
        self.seen_sync_committee_messages = SeenSyncCommitteeMessages()
        self.seen_sync_contributions = SeenSyncCommitteeMessages()
        self.seen_bls_to_execution_changes = SeenBlsToExecutionChanges()

        # block pipeline
        self.block_queue: JobItemQueue = JobItemQueue(
            self._process_block_job,
            max_length=BLOCK_QUEUE_LENGTH,
            queue_type=QueueType.FIFO,
            max_concurrency=1,
            name="block-processor",
        )
        self._event_handlers: Dict[ChainEvent, List[Callable]] = {}
        self.head_root: bytes = anchor_root
        self.db.block.put(
            anchor_root, _genesis_signed_block(anchor_hdr, anchor_state)
        )

    # ------------------------------------------------------------------
    # events
    # ------------------------------------------------------------------

    def on(self, event: ChainEvent, handler: Callable) -> None:
        self._event_handlers.setdefault(event, []).append(handler)

    def _emit(self, event: ChainEvent, *args) -> None:
        for h in self._event_handlers.get(event, []):
            h(*args)

    # ------------------------------------------------------------------
    # block pipeline
    # ------------------------------------------------------------------

    async def process_block(self, signed_block) -> bytes:
        """Queue a gossip/sync block for verification + import; resolves
        with the block root (chain.ts processBlock -> BlockProcessor)."""
        return await self.block_queue.push(signed_block)

    async def process_block_and_blobs(self, pair) -> bytes:
        """eip4844 import: validate the BlobsSidecar against the block's
        commitments, import the block, then persist the sidecar keyed by
        the block root (the reference's block-and-blobs import flow)."""
        from .validation import validate_blobs_sidecar

        signed_block = pair.beacon_block
        block = signed_block.message
        root = type(block).hash_tree_root(block)
        validate_blobs_sidecar(
            block.slot,
            root,
            list(block.body.blob_kzg_commitments),
            pair.blobs_sidecar,
        )
        out = await self.process_block(signed_block)
        self.db.blobs_sidecar.add(pair.blobs_sidecar)
        return out

    async def _process_block_job(self, signed_block) -> bytes:
        block = signed_block.message
        root = type(block).hash_tree_root(block)

        # sanity checks (verifyBlocksSanityChecks.ts)
        if self.db.block.has(root):
            return root  # already known
        current_slot = max(self.clock.current_slot, self.fork_choice.store.current_slot)
        if block.slot > current_slot:
            raise ValueError(f"future block slot {block.slot} > {current_slot}")
        fin = self.fork_choice.store.finalized
        if block.slot <= fin.epoch * _p.SLOTS_PER_EPOCH:
            raise ValueError("block older than finalized checkpoint")
        parent_root = bytes(block.parent_root)
        parent_node = self.fork_choice.get_block(_hex(parent_root))
        if parent_node is None:
            raise ValueError(f"unknown parent {parent_root.hex()}")
        if parent_node.execution_status is ExecutionStatus.Invalid:
            # the EL convicted the parent's payload: descendants are
            # invalid by construction and must not re-enter the pipeline
            raise ValueError(
                f"parent {parent_root.hex()} payload was invalidated by the EL"
            )

        pre_state = self.regen.get_pre_state(parent_root, block.slot)
        received_at = time.time()
        t_start = time.perf_counter()

        # 3-way parallel verify (verifyBlock.ts:71-80): execution payload ∥
        # state transition ∥ signature sets
        loop = asyncio.get_running_loop()

        async def verify_payload():
            from lodestar_tpu.execution.engine import (
                ExecutePayloadStatus,
                PayloadStatus,
            )

            if self.execution_engine is None:
                return None
            payload = getattr(block.body, "execution_payload", None)
            if payload is None:
                return None
            if bytes(payload.block_hash) == b"\x00" * 32:
                # pre-transition block: the default (empty) payload never
                # reaches an EL (spec: process_execution_payload skipped)
                return None
            # spec validate_merge_block: the transition block's payload
            # parent must be a valid terminal PoW block (verified through
            # the merge tracker when one is attached — eth1MergeBlockTracker
            # role, verifyBlocksExecutionPayloads.ts).
            if self.merge_tracker is not None:
                from lodestar_tpu.state_transition.block.bellatrix import (
                    is_merge_transition_block,
                )

                if is_merge_transition_block(pre_state.state, block.body):
                    ok = await self.merge_tracker.validate_merge_block(
                        bytes(payload.parent_hash)
                    )
                    if not ok:
                        raise ValueError("invalid terminal pow block")
            # eip4844 (engine_newPayloadV3) wants the blob versioned
            # hashes + parent beacon block root alongside the payload
            kwargs = {}
            commitments = getattr(block.body, "blob_kzg_commitments", None)
            if commitments is not None:
                from lodestar_tpu.state_transition.block.eip4844 import (
                    kzg_commitment_to_versioned_hash,
                )

                kwargs = dict(
                    versioned_hashes=[
                        kzg_commitment_to_versioned_hash(c) for c in commitments
                    ],
                    parent_beacon_block_root=bytes(block.parent_root),
                )
            try:
                res = await self.execution_engine.notify_new_payload(
                    payload, **kwargs
                )
            except asyncio.CancelledError:
                raise
            except Exception as e:
                # an unreachable/erroring EL must DOWNGRADE the import to
                # optimistic, not fail the block (sync/optimistic.md):
                # the chain keeps following head and re-validates later
                self._set_el_offline(True)
                _log.warn(
                    f"engine newPayload unavailable for block "
                    f"0x{root.hex()[:8]} ({type(e).__name__}: {e}); "
                    f"importing optimistically"
                )
                if self.metrics:
                    self.metrics.lodestar.engine_new_payload_total.labels(
                        status="engine_unavailable"
                    ).inc()
                return PayloadStatus(
                    ExecutePayloadStatus.SYNCING,
                    validation_error=f"engine unavailable: {e!r}",
                )
            self._set_el_offline(False)
            if self.metrics and res is not None:
                self.metrics.lodestar.engine_new_payload_total.labels(
                    status=str(getattr(res.status, "value", res.status)).lower()
                ).inc()
            return res

        def run_stf():
            t0 = time.perf_counter()
            post = state_transition(
                pre_state, signed_block,
                verify_state_root=True, verify_proposer=False,
                verify_signatures=False,
            )
            if self.metrics:
                self.metrics.lodestar.stfn_seconds.observe(time.perf_counter() - t0)
            return post

        async def verify_signatures():
            sets = get_block_signature_sets(
                self.cfg, pre_state.state, pre_state.epoch_ctx, signed_block
            )
            if not sets:
                return True
            t0 = time.perf_counter()
            ok = await self.bls.verify_signature_sets(
                sets, VerifyOptions(batchable=True)
            )
            if self.metrics:
                self.metrics.lodestar.block_sig_verify_seconds.observe(
                    time.perf_counter() - t0
                )
            return ok

        # all three branches settle before any error propagates —
        # otherwise a failing branch would leave the executor STF /
        # device verify running detached with unretrieved exceptions
        payload_res, post_state, sigs_ok = await gather_settled(
            verify_payload(),
            loop.run_in_executor(None, run_stf),
            verify_signatures(),
        )
        from lodestar_tpu.execution.engine import ExecutePayloadStatus

        if (
            payload_res is not None
            and payload_res.status is ExecutePayloadStatus.INVALID
        ):
            # the rejected block never enters fork choice, but
            # latestValidHash may convict already-imported (optimistic)
            # ancestors: everything above it on the parent chain
            lvh = payload_res.latest_valid_hash
            if lvh is not None and self.fork_choice.has_block(_hex(parent_root)):
                try:
                    self.on_invalid_execution_payload(
                        _hex(parent_root), _hex(bytes(lvh))
                    )
                except Exception as e:
                    # head recompute hiccups must not mask the INVALID
                    # verdict itself
                    _log.warn(
                        f"invalidation sweep after INVALID payload failed: "
                        f"{type(e).__name__}: {e}"
                    )
            raise ExecutionPayloadInvalidError(
                root,
                bytes(lvh) if lvh is not None else None,
                payload_res.validation_error,
            )
        if not sigs_ok:
            raise ValueError("block signatures invalid")

        self._import_block(
            signed_block, root, post_state, received_at, payload_res
        )
        if self.metrics:
            self.metrics.lodestar.block_import_seconds.observe(
                time.perf_counter() - t_start
            )
            self.metrics.lodestar.block_queue_length.set(len(self.block_queue))
            self.metrics.lodestar.state_cache_size.set(len(self.state_cache))
        return root

    def _import_block(
        self, signed_block, root, post_state, received_at, payload_res=None
    ) -> None:
        """importBlock.ts:46: persist, fork-choice, caches, events.
        ``payload_res`` is the EL's newPayload verdict (None when the
        block carries no payload or no engine is attached): VALID
        imports fully verified and de-optimisticizes the ancestor chain;
        SYNCING/ACCEPTED (incl. the engine-unavailable downgrade)
        imports optimistically."""
        from lodestar_tpu.execution.engine import ExecutePayloadStatus

        block = signed_block.message
        self.db.block.put(root, signed_block)
        self.state_cache.add(root, post_state)

        payload = getattr(block.body, "execution_payload", None)
        payload_hash_hex = None
        if payload is not None and bytes(payload.block_hash) != b"\x00" * 32:
            payload_hash_hex = _hex(bytes(payload.block_hash))
        if payload_hash_hex is None or payload_res is None:
            # no payload, pre-transition, or no engine attached: the
            # block is not subject to execution validity here
            exec_status = ExecutionStatus.PreMerge
        elif payload_res.status is ExecutePayloadStatus.VALID:
            exec_status = ExecutionStatus.Valid
        else:
            exec_status = ExecutionStatus.Optimistic

        st = post_state.state
        epoch = block.slot // _p.SLOTS_PER_EPOCH
        target_root = (
            root
            if block.slot % _p.SLOTS_PER_EPOCH == 0
            else bytes(st.block_roots[(epoch * _p.SLOTS_PER_EPOCH) % _p.SLOTS_PER_HISTORICAL_ROOT])
        )
        uj, uf = compute_unrealized_checkpoints(self.cfg, post_state)
        block_delay = max(
            0.0,
            received_at - (self.genesis_time + block.slot * self.cfg.SECONDS_PER_SLOT),
        )
        # capture BEFORE update_time: the epoch-boundary pull-up inside it
        # can itself advance justification/finalization
        old_fin = self.fork_choice.store.finalized.epoch
        old_just = self.fork_choice.store.justified.epoch
        self.fork_choice.update_time(
            max(self.clock.current_slot, block.slot)
        )
        self.fork_choice.on_block(
            ProtoBlock(
                slot=block.slot,
                block_root=_hex(root),
                parent_root=_hex(bytes(block.parent_root)),
                state_root=_hex(bytes(block.state_root)),
                target_root=_hex(target_root),
                justified_epoch=st.current_justified_checkpoint.epoch,
                justified_root=_hex(bytes(st.current_justified_checkpoint.root)),
                finalized_epoch=st.finalized_checkpoint.epoch,
                finalized_root=_hex(bytes(st.finalized_checkpoint.root)),
                unrealized_justified_epoch=uj.epoch,
                unrealized_justified_root=_hex(bytes(uj.root)),
                unrealized_finalized_epoch=uf.epoch,
                unrealized_finalized_root=_hex(bytes(uf.root)),
                execution_payload_block_hash=payload_hash_hex,
                execution_status=exec_status,
            ),
            block_delay_sec=block_delay,
            justified_checkpoint=CheckpointHex(
                st.current_justified_checkpoint.epoch,
                _hex(bytes(st.current_justified_checkpoint.root)),
            ),
            finalized_checkpoint=CheckpointHex(
                st.finalized_checkpoint.epoch,
                _hex(bytes(st.finalized_checkpoint.root)),
            ),
        )
        if exec_status is ExecutionStatus.Valid:
            # the EL validated this payload, which vouches for the whole
            # ancestor chain: de-flag any optimistically imported parents
            self.fork_choice.on_valid_execution(_hex(root))
        elif exec_status is ExecutionStatus.Optimistic:
            if self.metrics:
                self.metrics.lodestar.blocks_imported_optimistic_total.inc()
        # register the block's attestations as LMD votes (+ the validator
        # monitor's inclusion tracking, sharing the committee resolution)
        from lodestar_tpu.state_transition.block.phase0 import get_attesting_indices

        for att in block.body.attestations:
            try:
                indices = get_attesting_indices(
                    post_state.epoch_ctx, att.data, att.aggregation_bits
                )
                self.fork_choice.on_attestation(
                    indices,
                    _hex(bytes(att.data.beacon_block_root)),
                    att.data.target.epoch,
                )
                if self.metrics:
                    dist = max(1, block.slot - att.data.slot)
                    for idx in indices:
                        self.metrics.validator_monitor.on_attestation_in_block(
                            int(idx), att.data.target.epoch, dist
                        )
            except Exception as e:
                # vote outside cached shufflings — skip this att's
                # monitor update, but leave a trace
                _log.debug(
                    f"validator-monitor attestation skipped: "
                    f"{type(e).__name__}: {e}"
                )
                continue

        old_head_root = self.head_root
        head = self.fork_choice.update_head()
        self.head_root = bytes.fromhex(head.block_root[2:])
        self.seen_block_proposers.add(block.slot, block.proposer_index)
        if self.metrics:
            m = self.metrics
            m.beacon.head_slot.set(head.slot)
            m.beacon.current_justified_epoch.set(self.fork_choice.store.justified.epoch)
            m.beacon.finalized_epoch.set(self.fork_choice.store.finalized.epoch)
            m.beacon.proposed_blocks_total.inc()
            # reorg: the previous head is no longer an ancestor of the head
            if not self.fork_choice.is_descendant(_hex(old_head_root), head.block_root):
                m.beacon.reorgs_total.inc()
            m.validator_monitor.on_block_imported(block.proposer_index, epoch)

        self._emit(ChainEvent.block, signed_block, root)
        self._emit(ChainEvent.head, self.head_root)
        store = self.fork_choice.store
        if store.justified.epoch > old_just:
            self._emit(ChainEvent.justified, store.justified)
        if store.finalized.epoch > old_fin:
            self._emit(ChainEvent.finalized, store.finalized)
            # move the regen terminal pin to the new finalized state
            fin_root = bytes.fromhex(store.finalized.root[2:])
            if self.state_cache.get(fin_root) is not None:
                self.state_cache.pin(fin_root)
                if self._pinned_finalized_root != fin_root:
                    self.state_cache.unpin(self._pinned_finalized_root)
                    self._pinned_finalized_root = fin_root
            fin_epoch = store.finalized.epoch
            self.seen_attesters.prune(fin_epoch)
            self.seen_aggregators.prune(fin_epoch)
            self.seen_aggregated_attestations.prune(fin_epoch)
            self.attestation_pool.prune(self.clock.current_slot)
            self.aggregated_attestation_pool.prune(self.clock.current_slot)
            self.sync_committee_message_pool.prune(self.clock.current_slot)
            self.sync_contribution_pool.prune(self.clock.current_slot)
            fin_slot = fin_epoch * _p.SLOTS_PER_EPOCH
            self.seen_sync_committee_messages.prune(fin_slot)
            self.seen_sync_contributions.prune(fin_slot)

    # ------------------------------------------------------------------
    # optimistic sync (consensus-specs sync/optimistic.md; reference
    # importBlock.ts + forkChoice executionStatus tracking)
    # ------------------------------------------------------------------

    def _set_el_offline(self, offline: bool) -> None:
        self.el_offline = offline
        if self.metrics:
            self.metrics.lodestar.el_offline.set(1 if offline else 0)

    def is_optimistic_root(self, root_hex: str) -> bool:
        return self.fork_choice.is_optimistic(root_hex)

    def is_optimistic_head(self) -> bool:
        """True when the current head was imported without an EL verdict
        — such a head is followable but must never be proposed on."""
        return self.is_optimistic_root(_hex(self.head_root))

    def on_invalid_execution_payload(
        self, block_root_hex: str, latest_valid_hash_hex: Optional[str]
    ) -> List[str]:
        """An EL INVALID verdict anchored at ``block_root_hex``: prune
        the invalidated subtree from head selection and move head off
        it.  Returns the invalidated roots."""
        invalidated = self.fork_choice.on_invalid_execution(
            block_root_hex, latest_valid_hash_hex
        )
        if not invalidated:
            return invalidated
        if self.metrics:
            self.metrics.lodestar.blocks_invalidated_total.inc(len(invalidated))
        old_head_root = self.head_root
        head = self.fork_choice.update_head()
        self.head_root = bytes.fromhex(head.block_root[2:])
        _log.warn(
            f"EL invalidated {len(invalidated)} block(s) "
            f"(latestValidHash={latest_valid_hash_hex}); head moved "
            f"0x{old_head_root.hex()[:8]} -> {head.block_root[:10]}"
        )
        if self.head_root != old_head_root:
            if self.metrics:
                self.metrics.beacon.head_slot.set(head.slot)
                self.metrics.beacon.reorgs_total.inc()
            self._emit(ChainEvent.head, self.head_root)
        return invalidated

    async def notify_forkchoice_to_engine(self, payload_attributes=None):
        """Per-slot/per-head engine_forkchoiceUpdated notification (the
        reference's prepareExecutionPayload/notifyForkchoiceUpdate tick).
        Consumes the EL's verdict — VALID de-optimisticizes the head
        chain, INVALID prunes it — and NEVER raises on an unreachable
        EL: the clock loop must survive a dead or lying EL.  Returns the
        minted payloadId (attributes flows) or None."""
        from lodestar_tpu.execution.engine import ExecutePayloadStatus

        if self.execution_engine is None:
            return None
        head = self.fork_choice.get_head()
        head_hash_hex = head.execution_payload_block_hash
        if head_hash_hex is None:
            return None  # pre-merge head: nothing to tell an EL yet

        def _cp_payload_hash(cp_root_hex: str) -> bytes:
            node = self.fork_choice.get_block(cp_root_hex)
            h = node.execution_payload_block_hash if node is not None else None
            return bytes.fromhex(h[2:]) if h is not None else b"\x00" * 32

        store = self.fork_choice.store
        try:
            res = await self.execution_engine.notify_forkchoice_update(
                bytes.fromhex(head_hash_hex[2:]),
                _cp_payload_hash(store.justified.root),
                _cp_payload_hash(store.finalized.root),
                payload_attributes=payload_attributes,
                # engine structure version follows the head's fork
                # (V1/V2/V3); defaulting would pin capella+ chains to
                # V1 and strict ELs reject the mismatch
                fork=self._fork_config.fork_name_at_slot(head.slot),
            )
        except asyncio.CancelledError:
            raise
        except Exception as e:
            self._set_el_offline(True)
            _log.warn(
                f"engine forkchoiceUpdated failed ({type(e).__name__}: {e}); "
                f"keeping optimistic head"
            )
            return None
        self._set_el_offline(False)
        status = res.status
        if status.status is ExecutePayloadStatus.INVALID:
            lvh = status.latest_valid_hash
            self.on_invalid_execution_payload(
                head.block_root, _hex(bytes(lvh)) if lvh is not None else None
            )
            return None
        if status.status is ExecutePayloadStatus.VALID:
            self.fork_choice.on_valid_execution(head.block_root)
        return res.payload_id

    # ------------------------------------------------------------------

    def get_checkpoint_state(
        self, epoch: int, root: bytes
    ) -> Optional[CachedBeaconState]:
        """State of checkpoint (epoch, block root): the block's post-state
        dialed forward to the epoch's first slot (regen.getCheckpointState).
        Used for attestation-shuffling resolution and justified balances."""
        st = self.checkpoint_state_cache.get(epoch, root)
        if st is not None:
            return st
        base = self.state_cache.get(root)
        if base is None:
            try:
                base = self.regen._replay_to(root)
            except Exception as e:
                # regen miss: None is this API's answer, but the replay
                # failure itself must not vanish
                _log.debug(
                    f"checkpoint-state regen failed for "
                    f"0x{root.hex()[:8]}: {type(e).__name__}: {e}"
                )
                return None
        boundary_slot = epoch * _p.SLOTS_PER_EPOCH
        if base.state.slot < boundary_slot:
            from lodestar_tpu.state_transition import process_slots

            base = base.clone()
            process_slots(base, boundary_slot)
        self.checkpoint_state_cache.add(epoch, root, base)
        return base

    def _get_justified_balances(self, checkpoint) -> Optional[List[int]]:
        """Effective-balance increments of the justified checkpoint's state
        (the reference's justifiedBalancesGetter).  Called by ForkChoice on
        every justified change, including the balance-less on-tick pull-up."""
        st = self.get_checkpoint_state(
            checkpoint.epoch, bytes.fromhex(checkpoint.root[2:])
        )
        if st is None:
            return None
        return list(st.epoch_ctx.effective_balance_increments)

    def get_head_state(self) -> CachedBeaconState:
        st = self.state_cache.get(self.head_root)
        if st is None:
            st = self.regen.get_pre_state(self.head_root, 0)
        return st

    async def close(self) -> None:
        self.block_queue.abort()
        await self.bls.close()
        # HttpExecutionEngine keeps a reused aiohttp session; release it
        # with the chain so shutdown doesn't leak the connector FD
        eng_close = getattr(self.execution_engine, "close", None)
        if eng_close is not None:
            await eng_close()


def _genesis_signed_block(anchor_hdr, anchor_state):
    """Placeholder stored block for the anchor root so regen can stop
    there; body is empty (the anchor state itself is the source of truth)."""
    from lodestar_tpu.types import fork_of_state, types_for

    _, _, signed_type, _ = types_for(fork_of_state(anchor_state))
    b = signed_type.default()
    b.message.slot = anchor_hdr.slot
    b.message.proposer_index = anchor_hdr.proposer_index
    b.message.parent_root = bytes(anchor_hdr.parent_root)
    b.message.state_root = bytes(anchor_hdr.state_root)
    return b
