"""Slot clock (reference:
packages/beacon-node/src/chain/clock/LocalClock.ts:14 — slot ticker off
genesis time with epoch/slot events).
"""
from __future__ import annotations

import asyncio
import time
from typing import Awaitable, Callable, List, Optional

from lodestar_tpu.params import ACTIVE_PRESET as _p


class LocalClock:
    def __init__(self, genesis_time: int, seconds_per_slot: int, now: Callable[[], float] = time.time):
        self.genesis_time = genesis_time
        self.seconds_per_slot = seconds_per_slot
        self._now = now
        self._on_slot: List[Callable[[int], Awaitable[None]]] = []
        self._on_epoch: List[Callable[[int], Awaitable[None]]] = []
        self._task: Optional[asyncio.Task] = None

    @property
    def current_slot(self) -> int:
        return max(0, int((self._now() - self.genesis_time) // self.seconds_per_slot))

    @property
    def current_epoch(self) -> int:
        return self.current_slot // _p.SLOTS_PER_EPOCH

    def slot_start_time(self, slot: int) -> float:
        return self.genesis_time + slot * self.seconds_per_slot

    def seconds_into_slot(self) -> float:
        return (self._now() - self.genesis_time) % self.seconds_per_slot

    def on_slot(self, cb: Callable[[int], Awaitable[None]]) -> None:
        self._on_slot.append(cb)

    def on_epoch(self, cb: Callable[[int], Awaitable[None]]) -> None:
        self._on_epoch.append(cb)

    def start(self) -> None:
        if self._task is None:
            self._task = asyncio.ensure_future(self._tick_loop())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _tick_loop(self) -> None:
        last_slot = self.current_slot
        while True:
            next_slot = last_slot + 1
            wait = self.slot_start_time(next_slot) - self._now()
            if wait > 0:
                await asyncio.sleep(wait)
            last_slot = next_slot
            for cb in self._on_slot:
                await cb(next_slot)
            if next_slot % _p.SLOTS_PER_EPOCH == 0:
                for cb in self._on_epoch:
                    await cb(next_slot // _p.SLOTS_PER_EPOCH)
