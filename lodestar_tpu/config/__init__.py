"""Runtime chain configuration — the rebuild's `@lodestar/config`.

Mirrors packages/config/src: IChainConfig runtime variables
(chainConfig/types.ts), the mainnet/minimal defaults
(chainConfig/presets/{mainnet,minimal}.ts), the fork schedule helpers
(forkConfig/), and the genesis-anchored BeaconConfig with cached fork
digests (beaconConfig.ts).  YAML config loading follows the
consensus-specs config file format (chainConfig/json.ts role).
"""
from __future__ import annotations

import hashlib
from dataclasses import dataclass, field, replace
from typing import Dict, List, Optional

from lodestar_tpu.params import (
    ACTIVE_PRESET_NAME,
    FORK_ORDER,
    FORK_SEQ,
    ForkName,
    SLOTS_PER_EPOCH,
)

FAR_FUTURE_EPOCH = 2**64 - 1


@dataclass(frozen=True)
class ChainConfig:
    PRESET_BASE: str = "mainnet"
    CONFIG_NAME: str = "mainnet"
    # Transition
    TERMINAL_TOTAL_DIFFICULTY: int = 58750000000000000000000
    TERMINAL_BLOCK_HASH: bytes = b"\x00" * 32
    TERMINAL_BLOCK_HASH_ACTIVATION_EPOCH: int = FAR_FUTURE_EPOCH
    # Genesis
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT: int = 16384
    MIN_GENESIS_TIME: int = 1606824000
    GENESIS_FORK_VERSION: bytes = bytes.fromhex("00000000")
    GENESIS_DELAY: int = 604800
    # Forking
    ALTAIR_FORK_VERSION: bytes = bytes.fromhex("01000000")
    ALTAIR_FORK_EPOCH: int = 74240
    BELLATRIX_FORK_VERSION: bytes = bytes.fromhex("02000000")
    BELLATRIX_FORK_EPOCH: int = 144896
    CAPELLA_FORK_VERSION: bytes = bytes.fromhex("03000000")
    CAPELLA_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    EIP4844_FORK_VERSION: bytes = bytes.fromhex("04000000")
    EIP4844_FORK_EPOCH: int = FAR_FUTURE_EPOCH
    # Time
    SECONDS_PER_SLOT: int = 12
    SECONDS_PER_ETH1_BLOCK: int = 14
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY: int = 256
    SHARD_COMMITTEE_PERIOD: int = 256
    ETH1_FOLLOW_DISTANCE: int = 2048
    # Validator cycle
    INACTIVITY_SCORE_BIAS: int = 4
    INACTIVITY_SCORE_RECOVERY_RATE: int = 16
    EJECTION_BALANCE: int = 16000000000
    MIN_PER_EPOCH_CHURN_LIMIT: int = 4
    CHURN_LIMIT_QUOTIENT: int = 65536
    # Proposer boost
    PROPOSER_SCORE_BOOST: int = 40
    # Deposit contract
    DEPOSIT_CHAIN_ID: int = 1
    DEPOSIT_NETWORK_ID: int = 1
    DEPOSIT_CONTRACT_ADDRESS: bytes = bytes.fromhex(
        "00000000219ab540356cbb839cbe05303d7705fa"
    )
    # EIP-4844
    MAX_REQUEST_BLOBS_SIDECARS: int = 128
    MIN_EPOCHS_FOR_BLOBS_SIDECARS_REQUESTS: int = 4096


mainnet_chain_config = ChainConfig()

minimal_chain_config = ChainConfig(
    PRESET_BASE="minimal",
    CONFIG_NAME="minimal",
    MIN_GENESIS_ACTIVE_VALIDATOR_COUNT=64,
    MIN_GENESIS_TIME=1578009600,
    GENESIS_FORK_VERSION=bytes.fromhex("00000001"),
    GENESIS_DELAY=300,
    ALTAIR_FORK_VERSION=bytes.fromhex("01000001"),
    ALTAIR_FORK_EPOCH=FAR_FUTURE_EPOCH,
    BELLATRIX_FORK_VERSION=bytes.fromhex("02000001"),
    BELLATRIX_FORK_EPOCH=FAR_FUTURE_EPOCH,
    CAPELLA_FORK_VERSION=bytes.fromhex("03000001"),
    EIP4844_FORK_VERSION=bytes.fromhex("04000001"),
    SECONDS_PER_SLOT=6,
    MIN_VALIDATOR_WITHDRAWABILITY_DELAY=256,
    SHARD_COMMITTEE_PERIOD=64,
    ETH1_FOLLOW_DISTANCE=16,
    EJECTION_BALANCE=16000000000,
    MIN_PER_EPOCH_CHURN_LIMIT=4,
    CHURN_LIMIT_QUOTIENT=32,
    DEPOSIT_CHAIN_ID=5,
    DEPOSIT_NETWORK_ID=5,
    DEPOSIT_CONTRACT_ADDRESS=bytes.fromhex("1234567890123456789012345678901234567890"),
)

# default config matches the active compile-time preset, like config/default.ts
default_chain_config = (
    mainnet_chain_config if ACTIVE_PRESET_NAME == "mainnet" else minimal_chain_config
)


@dataclass(frozen=True)
class ForkInfo:
    name: ForkName
    epoch: int
    version: bytes
    prev_version: bytes
    prev_fork_name: ForkName


class ForkConfig:
    """Fork schedule lookups (packages/config/src/forkConfig/index.ts)."""

    def __init__(self, chain: ChainConfig):
        self.chain = chain
        epochs = {
            ForkName.phase0: 0,
            ForkName.altair: chain.ALTAIR_FORK_EPOCH,
            ForkName.bellatrix: chain.BELLATRIX_FORK_EPOCH,
            ForkName.capella: chain.CAPELLA_FORK_EPOCH,
            ForkName.eip4844: chain.EIP4844_FORK_EPOCH,
        }
        versions = {
            ForkName.phase0: chain.GENESIS_FORK_VERSION,
            ForkName.altair: chain.ALTAIR_FORK_VERSION,
            ForkName.bellatrix: chain.BELLATRIX_FORK_VERSION,
            ForkName.capella: chain.CAPELLA_FORK_VERSION,
            ForkName.eip4844: chain.EIP4844_FORK_VERSION,
        }
        self.forks: Dict[ForkName, ForkInfo] = {}
        prev = ForkName.phase0
        for f in FORK_ORDER:
            self.forks[f] = ForkInfo(
                name=f,
                epoch=epochs[f],
                version=versions[f],
                prev_version=versions[prev],
                prev_fork_name=prev,
            )
            if epochs[f] < FAR_FUTURE_EPOCH:
                prev = f
        # scheduled forks sorted ascending by epoch, phase0 first
        self.forks_ascending: List[ForkInfo] = sorted(
            self.forks.values(), key=lambda fi: (fi.epoch, FORK_SEQ[fi.name])
        )

    def fork_name_at_epoch(self, epoch: int) -> ForkName:
        out = ForkName.phase0
        for fi in self.forks_ascending:
            if fi.epoch <= epoch:
                out = fi.name
        return out

    def fork_name_at_slot(self, slot: int) -> ForkName:
        return self.fork_name_at_epoch(slot // SLOTS_PER_EPOCH)

    def fork_at_epoch(self, epoch: int) -> ForkInfo:
        return self.forks[self.fork_name_at_epoch(epoch)]

    def fork_version_at_epoch(self, epoch: int) -> bytes:
        return self.fork_at_epoch(epoch).version


def compute_fork_data_root(version: bytes, genesis_validators_root: bytes) -> bytes:
    """hash_tree_root(ForkData) without importing the types package (it is
    a 2-field fixed container: sha256(version32 || gvr))."""
    return hashlib.sha256(version.ljust(32, b"\x00") + genesis_validators_root).digest()


def compute_fork_digest(version: bytes, genesis_validators_root: bytes) -> bytes:
    return compute_fork_data_root(version, genesis_validators_root)[:4]


class BeaconConfig(ForkConfig):
    """ForkConfig + genesis anchor: cached fork digests per fork
    (packages/config/src/beaconConfig.ts createCachedGenesis)."""

    def __init__(self, chain: ChainConfig, genesis_validators_root: bytes):
        super().__init__(chain)
        self.genesis_validators_root = genesis_validators_root
        self._digest_by_fork: Dict[ForkName, bytes] = {}
        self._fork_by_digest: Dict[bytes, ForkName] = {}
        for f in FORK_ORDER:
            d = compute_fork_digest(self.forks[f].version, genesis_validators_root)
            self._digest_by_fork[f] = d
            # first fork wins for duplicate digests (unscheduled forks share
            # the digest of the fork whose version they inherit)
            self._fork_by_digest.setdefault(d, f)

    def fork_digest(self, fork: ForkName) -> bytes:
        return self._digest_by_fork[fork]

    def fork_digest_at_slot(self, slot: int) -> bytes:
        return self._digest_by_fork[self.fork_name_at_slot(slot)]

    def fork_from_digest(self, digest: bytes) -> ForkName:
        if digest not in self._fork_by_digest:
            raise ValueError(f"unknown fork digest {digest.hex()}")
        return self._fork_by_digest[digest]


def create_fork_config(chain: ChainConfig) -> ForkConfig:
    return ForkConfig(chain)


def create_beacon_config(
    chain: ChainConfig, genesis_validators_root: bytes
) -> BeaconConfig:
    return BeaconConfig(chain, genesis_validators_root)


def chain_config_from_dict(data: dict, base: Optional[ChainConfig] = None) -> ChainConfig:
    """Build a ChainConfig from a consensus-specs YAML-style dict (string
    values allowed, hex strings for bytes fields) layered over `base`."""
    base = base or default_chain_config
    kwargs = {}
    for fname, f in ChainConfig.__dataclass_fields__.items():
        if fname not in data:
            continue
        raw = data[fname]
        cur = getattr(base, fname)
        if isinstance(cur, bytes):
            s = raw if isinstance(raw, str) else str(raw)
            kwargs[fname] = bytes.fromhex(s.removeprefix("0x"))
        elif isinstance(cur, bool):
            kwargs[fname] = bool(raw)
        elif isinstance(cur, int):
            kwargs[fname] = int(raw)
        else:
            kwargs[fname] = raw
    return replace(base, **kwargs)
