"""Altair epoch processing (reference:
packages/state-transition/src/epoch/ altair branches; consensus-specs
altair/beacon-chain.md epoch processing).

Same flat-array strategy as phase0: the per-validator participation FLAG
bytes already live in the state as uint8 lists, so before_process_epoch
just views them as numpy arrays — the altair state layout is exactly the
vectorized representation phase0 had to reconstruct from attestations
(SURVEY §2.4 note).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Optional

import numpy as np

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    GENESIS_EPOCH,
    PARTICIPATION_FLAG_WEIGHTS,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
)
from lodestar_tpu.types import ssz
from ..epoch_context import EpochContext
from ..util.misc import compute_epoch_at_slot
from ..util.sync_committee import get_next_sync_committee
from . import phase0 as e0


@dataclass
class AltairEpochProcess:
    current_epoch: int
    previous_epoch: int
    total_active_balance: int
    prev_participation: np.ndarray   # uint8 flag bytes
    curr_participation: np.ndarray
    effective_balances: np.ndarray   # int64 gwei
    unslashed: np.ndarray            # bool
    is_active_prev: np.ndarray
    is_active_curr: np.ndarray
    eligible: np.ndarray
    balances: Optional[np.ndarray] = None


def before_process_epoch(cfg, state, epoch_ctx: EpochContext) -> AltairEpochProcess:
    current_epoch = compute_epoch_at_slot(state.slot)
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)

    eff = np.array([v.effective_balance for v in state.validators], dtype=np.int64)
    slashed = np.array([v.slashed for v in state.validators], dtype=bool)
    activation = np.array(
        [v.activation_epoch for v in state.validators], dtype=np.float64
    )
    exit_e = np.array([v.exit_epoch for v in state.validators], dtype=np.float64)
    withdrawable = np.array(
        [v.withdrawable_epoch for v in state.validators], dtype=np.float64
    )
    is_active_prev = (activation <= previous_epoch) & (previous_epoch < exit_e)
    is_active_curr = (activation <= current_epoch) & (current_epoch < exit_e)
    eligible = is_active_prev | (slashed & (previous_epoch + 1 < withdrawable))

    prev_part = np.array(state.previous_epoch_participation, dtype=np.uint8)
    curr_part = np.array(state.current_epoch_participation, dtype=np.uint8)

    total_active = int(eff[is_active_curr].sum())
    return AltairEpochProcess(
        current_epoch=current_epoch,
        previous_epoch=previous_epoch,
        total_active_balance=max(_p.EFFECTIVE_BALANCE_INCREMENT, total_active),
        prev_participation=prev_part,
        curr_participation=curr_part,
        effective_balances=eff,
        unslashed=~slashed,
        is_active_prev=is_active_prev,
        is_active_curr=is_active_curr,
        eligible=eligible,
    )


def _unslashed_participating_balance(
    proc: AltairEpochProcess, flag_index: int, previous: bool
) -> int:
    part = proc.prev_participation if previous else proc.curr_participation
    active = proc.is_active_prev if previous else proc.is_active_curr
    m = active & proc.unslashed & ((part & (1 << flag_index)) != 0)
    return max(_p.EFFECTIVE_BALANCE_INCREMENT, int(proc.effective_balances[m].sum()))


def process_justification_and_finalization(cfg, state, proc) -> None:
    if proc.current_epoch <= GENESIS_EPOCH + 1:
        return
    prev_target = _unslashed_participating_balance(
        proc, TIMELY_TARGET_FLAG_INDEX, previous=True
    )
    curr_target = _unslashed_participating_balance(
        proc, TIMELY_TARGET_FLAG_INDEX, previous=False
    )
    e0.weigh_justification_and_finalization(
        cfg, state, proc.total_active_balance, prev_target, curr_target
    )


# ---------------------------------------------------------------------------
# inactivity + rewards
# ---------------------------------------------------------------------------


def _finality_delay(proc, state) -> int:
    return proc.previous_epoch - state.finalized_checkpoint.epoch


def is_in_inactivity_leak(proc, state) -> bool:
    return _finality_delay(proc, state) > _p.MIN_EPOCHS_TO_INACTIVITY_PENALTY


def process_inactivity_updates(cfg, state, proc: AltairEpochProcess) -> None:
    if proc.current_epoch == GENESIS_EPOCH:
        return
    scores = np.array(state.inactivity_scores, dtype=np.int64)
    prev_target = (
        proc.unslashed
        & proc.is_active_prev
        & ((proc.prev_participation & (1 << TIMELY_TARGET_FLAG_INDEX)) != 0)
    )
    leaking = is_in_inactivity_leak(proc, state)
    # eligible validators only
    el = proc.eligible
    inc = el & ~prev_target
    scores[el & prev_target] = np.maximum(0, scores[el & prev_target] - 1)
    scores[inc] += cfg.INACTIVITY_SCORE_BIAS
    if not leaking:
        scores[el] = np.maximum(
            0, scores[el] - cfg.INACTIVITY_SCORE_RECOVERY_RATE
        )
    # bulk write-back (non-eligible entries are unchanged values): one
    # tracked-list rebuild instead of ~n per-index tracked writes
    state.inactivity_scores[:] = scores.tolist()


def get_flag_index_deltas(cfg, state, proc: AltairEpochProcess, flag_index: int):
    """Per-flag (rewards, penalties) arrays — spec get_flag_index_deltas.
    Exposed separately so the rewards conformance runner
    (spec_test/runners.py make_rewards_runner) can emit the official
    per-component Deltas files."""
    import math

    n = len(proc.effective_balances)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    increment = _p.EFFECTIVE_BALANCE_INCREMENT
    base_reward_per_increment = (
        increment * _p.BASE_REWARD_FACTOR // math.isqrt(proc.total_active_balance)
    )
    base_rewards = (proc.effective_balances // increment) * base_reward_per_increment
    total_incr = proc.total_active_balance // increment
    leaking = is_in_inactivity_leak(proc, state)
    weight = PARTICIPATION_FLAG_WEIGHTS[flag_index]

    participating = (
        proc.unslashed
        & proc.is_active_prev
        & ((proc.prev_participation & (1 << flag_index)) != 0)
    )
    unslashed_incr = (
        max(increment, int(proc.effective_balances[participating].sum()))
        // increment
    )
    mask_r = proc.eligible & participating
    mask_p = proc.eligible & ~participating
    if not leaking:
        reward_numerator = base_rewards[mask_r] * weight * unslashed_incr
        rewards[mask_r] += reward_numerator // (total_incr * WEIGHT_DENOMINATOR)
    if flag_index != TIMELY_HEAD_FLAG_INDEX:
        penalties[mask_p] += base_rewards[mask_p] * weight // WEIGHT_DENOMINATOR
    return rewards, penalties


def get_inactivity_penalty_deltas(cfg, state, proc: AltairEpochProcess):
    """Spec get_inactivity_penalty_deltas (zero rewards by construction)."""
    n = len(proc.effective_balances)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    scores = np.array(state.inactivity_scores, dtype=np.int64)
    prev_target = (
        proc.unslashed
        & proc.is_active_prev
        & ((proc.prev_participation & (1 << TIMELY_TARGET_FLAG_INDEX)) != 0)
    )
    mask = proc.eligible & ~prev_target
    from lodestar_tpu.types import fork_of_state
    from ..fork_params import inactivity_penalty_quotient

    penalty_den = cfg.INACTIVITY_SCORE_BIAS * inactivity_penalty_quotient(
        fork_of_state(state)
    )
    penalties[mask] += (
        proc.effective_balances[mask] * scores[mask] // penalty_den
    )
    return rewards, penalties


def get_flag_deltas(cfg, state, proc: AltairEpochProcess):
    """Vectorized altair get_flag_index_deltas + inactivity penalties."""
    n = len(proc.effective_balances)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    for flag_index in range(len(PARTICIPATION_FLAG_WEIGHTS)):
        r, p = get_flag_index_deltas(cfg, state, proc, flag_index)
        rewards += r
        penalties += p
    r, p = get_inactivity_penalty_deltas(cfg, state, proc)
    rewards += r
    penalties += p
    return rewards, penalties


def process_rewards_and_penalties(cfg, state, proc: AltairEpochProcess) -> None:
    if proc.current_epoch == GENESIS_EPOCH:
        return
    rewards, penalties = get_flag_deltas(cfg, state, proc)
    balances = np.array(state.balances, dtype=np.int64)
    balances = np.maximum(0, balances + rewards - penalties)
    # bulk write-back: a slice assignment costs ONE incremental-tree
    # rebuild of the balances subtree (~25 ms native at 250k) instead of
    # 250k tracked per-index writes (~1.2 s of Python)
    state.balances[:] = balances.tolist()
    proc.balances = balances


def process_slashings(cfg, state, proc: AltairEpochProcess) -> None:
    from lodestar_tpu.types import fork_of_state
    from ..fork_params import proportional_slashing_multiplier

    epoch = proc.current_epoch
    total_balance = proc.total_active_balance
    total_slashings = sum(state.slashings)
    mult = min(
        total_slashings * proportional_slashing_multiplier(fork_of_state(state)),
        total_balance,
    )
    for i, v in enumerate(state.validators):
        if (
            v.slashed
            and epoch + _p.EPOCHS_PER_SLASHINGS_VECTOR // 2 == v.withdrawable_epoch
        ):
            increment = _p.EFFECTIVE_BALANCE_INCREMENT
            penalty_numerator = v.effective_balance // increment * mult
            penalty = penalty_numerator // total_balance * increment
            state.balances[i] = max(0, state.balances[i] - penalty)


def process_participation_flag_updates(cfg, state, proc) -> None:
    state.previous_epoch_participation = list(state.current_epoch_participation)
    state.current_epoch_participation = [0] * len(state.validators)


def process_sync_committee_updates(cfg, state, proc, epoch_ctx: EpochContext) -> None:
    next_epoch = proc.current_epoch + 1
    if next_epoch % _p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD == 0:
        state.current_sync_committee = state.next_sync_committee
        committee, _ = get_next_sync_committee(
            state,
            epoch_ctx.next_shuffling.active_indices,
            [v.effective_balance for v in state.validators],
        )
        state.next_sync_committee = committee
        # invalidate the cached committee-indices lookup
        if hasattr(epoch_ctx, "_sync_committee_indices"):
            del epoch_ctx._sync_committee_indices


def process_historical_summaries_update(cfg, state, proc) -> None:
    """Capella replacement for historical_roots accumulation: append a
    HistoricalSummary of the two root vectors (consensus-specs capella
    beacon-chain.md process_historical_summaries_update)."""
    next_epoch = proc.current_epoch + 1
    if next_epoch % (_p.SLOTS_PER_HISTORICAL_ROOT // _p.SLOTS_PER_EPOCH) == 0:
        roots_t = ssz.capella.BeaconState._fields_["block_roots"]
        state.historical_summaries.append(
            ssz.capella.HistoricalSummary(
                block_summary_root=roots_t.hash_tree_root(list(state.block_roots)),
                state_summary_root=roots_t.hash_tree_root(list(state.state_roots)),
            )
        )


def process_epoch(cfg, state, epoch_ctx: EpochContext) -> AltairEpochProcess:
    from lodestar_tpu.params import ForkName
    from lodestar_tpu.types import fork_of_state
    from ..fork_params import is_post_fork

    proc = before_process_epoch(cfg, state, epoch_ctx)
    process_justification_and_finalization(cfg, state, proc)
    process_inactivity_updates(cfg, state, proc)
    process_rewards_and_penalties(cfg, state, proc)
    e0.process_registry_updates(cfg, state, proc, epoch_ctx)
    process_slashings(cfg, state, proc)
    e0.process_eth1_data_reset(cfg, state, proc)
    e0.process_effective_balance_updates(cfg, state, proc)
    e0.process_slashings_reset(cfg, state, proc)
    e0.process_randao_mixes_reset(cfg, state, proc)
    if is_post_fork(fork_of_state(state), ForkName.capella):
        process_historical_summaries_update(cfg, state, proc)
    else:
        e0.process_historical_roots_update(cfg, state, proc)
    process_participation_flag_updates(cfg, state, proc)
    process_sync_committee_updates(cfg, state, proc, epoch_ctx)
    return proc
