"""Phase0 epoch processing (reference:
packages/state-transition/src/epoch/*.ts; consensus-specs phase0).

The O(V) work runs over flat numpy arrays assembled once per transition
(the reference's beforeProcessEpoch / EpochProcess pattern,
cache/epochProcess.ts:126-140): per-validator participation flags,
effective balances, inclusion delays.  The tree-backed state is only
touched to read pending attestations and write back results.
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import Dict, List, Optional

import numpy as np

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    BASE_REWARDS_PER_EPOCH,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
)
from lodestar_tpu.types import ssz
from ..epoch_context import EpochContext
from ..util.misc import (
    compute_activation_exit_epoch,
    compute_epoch_at_slot,
    get_block_root,
    get_block_root_at_slot,
    get_randao_mix,
    get_validator_churn_limit,
    int_to_bytes,
)

FLAG_PREV_SOURCE = 1 << 0
FLAG_PREV_TARGET = 1 << 1
FLAG_PREV_HEAD = 1 << 2
FLAG_CURR_SOURCE = 1 << 3
FLAG_CURR_TARGET = 1 << 4
FLAG_UNSLASHED = 1 << 5
FLAG_ELIGIBLE = 1 << 6


@dataclass
class EpochProcess:
    """Flat per-validator arrays for one epoch transition."""

    current_epoch: int
    previous_epoch: int
    total_active_balance: int
    flags: np.ndarray               # uint8 flag bytes
    effective_balances: np.ndarray  # int64 gwei
    is_active_prev: np.ndarray      # bool
    is_active_curr: np.ndarray
    # earliest-inclusion info for prev-epoch source attesters
    inclusion_delay: np.ndarray     # int64 (0 = none)
    inclusion_proposer: np.ndarray  # int64 (-1 = none)
    balances: Optional[np.ndarray] = None


def _attesting_flags(state, epoch_ctx, attestations, epoch, flags, source_flag, target_flag, head_flag, incl_delay=None, incl_proposer=None):
    try:
        target_root = get_block_root(state, epoch)
    except ValueError:
        target_root = None
    for att in attestations:
        data = att.data
        committee = epoch_ctx.get_committee(data.slot, data.index)
        indices = [int(committee[i]) for i, b in enumerate(att.aggregation_bits) if b]
        matching_target = target_root is not None and bytes(data.target.root) == target_root
        matching_head = False
        if matching_target:
            try:
                matching_head = bytes(data.beacon_block_root) == get_block_root_at_slot(
                    state, data.slot
                )
            except ValueError:
                matching_head = False
        for i in indices:
            flags[i] |= source_flag
            if matching_target:
                flags[i] |= target_flag
            if matching_head:
                flags[i] |= head_flag
            if incl_delay is not None:
                d = att.inclusion_delay
                if incl_delay[i] == 0 or d < incl_delay[i]:
                    incl_delay[i] = d
                    incl_proposer[i] = att.proposer_index


def before_process_epoch(cfg, state, epoch_ctx: EpochContext) -> EpochProcess:
    n = len(state.validators)
    current_epoch = compute_epoch_at_slot(state.slot)
    previous_epoch = max(GENESIS_EPOCH, current_epoch - 1)

    eff = np.array([v.effective_balance for v in state.validators], dtype=np.int64)
    slashed = np.array([v.slashed for v in state.validators], dtype=bool)
    activation = np.array(
        [v.activation_epoch for v in state.validators], dtype=np.float64
    )
    exit_e = np.array([v.exit_epoch for v in state.validators], dtype=np.float64)
    withdrawable = np.array(
        [v.withdrawable_epoch for v in state.validators], dtype=np.float64
    )

    is_active_prev = (activation <= previous_epoch) & (previous_epoch < exit_e)
    is_active_curr = (activation <= current_epoch) & (current_epoch < exit_e)

    flags = np.zeros(n, dtype=np.uint8)
    flags[~slashed] |= FLAG_UNSLASHED
    eligible = is_active_prev | (slashed & (previous_epoch + 1 < withdrawable))
    flags[eligible] |= FLAG_ELIGIBLE

    incl_delay = np.zeros(n, dtype=np.int64)
    incl_proposer = np.full(n, -1, dtype=np.int64)

    _attesting_flags(
        state, epoch_ctx, state.previous_epoch_attestations, previous_epoch,
        flags, FLAG_PREV_SOURCE, FLAG_PREV_TARGET, FLAG_PREV_HEAD,
        incl_delay, incl_proposer,
    )
    _attesting_flags(
        state, epoch_ctx, state.current_epoch_attestations, current_epoch,
        flags, FLAG_CURR_SOURCE, FLAG_CURR_TARGET, 0,
    )

    total_active = int(eff[is_active_curr].sum())
    return EpochProcess(
        current_epoch=current_epoch,
        previous_epoch=previous_epoch,
        total_active_balance=max(_p.EFFECTIVE_BALANCE_INCREMENT, total_active),
        flags=flags,
        effective_balances=eff,
        is_active_prev=is_active_prev,
        is_active_curr=is_active_curr,
        inclusion_delay=incl_delay,
        inclusion_proposer=incl_proposer,
    )


def _unslashed_attesting_balance(proc: EpochProcess, flag: int) -> int:
    m = ((proc.flags & flag) != 0) & ((proc.flags & FLAG_UNSLASHED) != 0)
    return max(
        _p.EFFECTIVE_BALANCE_INCREMENT, int(proc.effective_balances[m].sum())
    )


# ---------------------------------------------------------------------------
# justification & finalization
# ---------------------------------------------------------------------------


def process_justification_and_finalization(cfg, state, proc: EpochProcess) -> None:
    if proc.current_epoch <= GENESIS_EPOCH + 1:
        return
    prev_target = _unslashed_attesting_balance(proc, FLAG_PREV_TARGET)
    curr_target = _unslashed_attesting_balance(proc, FLAG_CURR_TARGET)
    weigh_justification_and_finalization(
        cfg, state, proc.total_active_balance, prev_target, curr_target
    )


def weigh_justification_and_finalization(
    cfg, state, total_balance: int, previous_target: int, current_target: int
) -> None:
    current_epoch = compute_epoch_at_slot(state.slot)
    previous_epoch = current_epoch - 1
    old_prev = state.previous_justified_checkpoint
    old_curr = state.current_justified_checkpoint
    bits = list(state.justification_bits)

    state.previous_justified_checkpoint = state.current_justified_checkpoint
    bits = [False] + bits[:-1]

    if previous_target * 3 >= total_balance * 2:
        state.current_justified_checkpoint = ssz.phase0.Checkpoint(
            epoch=previous_epoch, root=get_block_root(state, previous_epoch)
        )
        bits[1] = True
    if current_target * 3 >= total_balance * 2:
        state.current_justified_checkpoint = ssz.phase0.Checkpoint(
            epoch=current_epoch, root=get_block_root(state, current_epoch)
        )
        bits[0] = True
    state.justification_bits = bits

    # finalization rules
    if all(bits[1:4]) and old_prev.epoch + 3 == current_epoch:
        state.finalized_checkpoint = old_prev
    if all(bits[1:3]) and old_prev.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_prev
    if all(bits[0:3]) and old_curr.epoch + 2 == current_epoch:
        state.finalized_checkpoint = old_curr
    if all(bits[0:2]) and old_curr.epoch + 1 == current_epoch:
        state.finalized_checkpoint = old_curr


# ---------------------------------------------------------------------------
# rewards & penalties (vectorized phase0 deltas)
# ---------------------------------------------------------------------------


def is_in_inactivity_leak(proc: EpochProcess, state) -> bool:
    return finality_delay(proc, state) > _p.MIN_EPOCHS_TO_INACTIVITY_PENALTY


def finality_delay(proc: EpochProcess, state) -> int:
    return proc.previous_epoch - state.finalized_checkpoint.epoch


def get_attestation_deltas(cfg, state, proc: EpochProcess):
    """Vectorized phase0 get_attestation_deltas: returns (rewards,
    penalties) int64 arrays."""
    n = len(proc.flags)
    rewards = np.zeros(n, dtype=np.int64)
    penalties = np.zeros(n, dtype=np.int64)
    sqrt_total = int(math.isqrt(proc.total_active_balance))
    base_rewards = (
        proc.effective_balances * _p.BASE_REWARD_FACTOR // sqrt_total // BASE_REWARDS_PER_EPOCH
    )
    proposer_rewards = base_rewards // _p.PROPOSER_REWARD_QUOTIENT
    eligible = (proc.flags & FLAG_ELIGIBLE) != 0
    unslashed = (proc.flags & FLAG_UNSLASHED) != 0
    in_leak = is_in_inactivity_leak(proc, state)
    total_incr = proc.total_active_balance // _p.EFFECTIVE_BALANCE_INCREMENT

    for flag in (FLAG_PREV_SOURCE, FLAG_PREV_TARGET, FLAG_PREV_HEAD):
        participated = ((proc.flags & flag) != 0) & unslashed
        comp_balance = _unslashed_attesting_balance(proc, flag)
        comp_incr = comp_balance // _p.EFFECTIVE_BALANCE_INCREMENT
        mask_r = eligible & participated
        mask_p = eligible & ~participated
        if in_leak:
            rewards[mask_r] += base_rewards[mask_r]
        else:
            rewards[mask_r] += (
                base_rewards[mask_r] * comp_incr // total_incr
            )
        penalties[mask_p] += base_rewards[mask_p]

    # inclusion delay: earliest matching-source inclusion
    src = ((proc.flags & FLAG_PREV_SOURCE) != 0) & unslashed & (proc.inclusion_delay > 0)
    idx = np.nonzero(src)[0]
    for i in idx:
        max_attester = base_rewards[i] - proposer_rewards[i]
        rewards[i] += max_attester // proc.inclusion_delay[i]
        p = proc.inclusion_proposer[i]
        if p >= 0:
            rewards[p] += proposer_rewards[i]

    if in_leak:
        delay = finality_delay(proc, state)
        penalties[eligible] += (
            BASE_REWARDS_PER_EPOCH * base_rewards[eligible] - proposer_rewards[eligible]
        )
        not_target = eligible & ~(((proc.flags & FLAG_PREV_TARGET) != 0) & unslashed)
        penalties[not_target] += (
            proc.effective_balances[not_target] * delay // _p.INACTIVITY_PENALTY_QUOTIENT
        )
    return rewards, penalties


def process_rewards_and_penalties(cfg, state, proc: EpochProcess) -> None:
    if proc.current_epoch == GENESIS_EPOCH:
        return
    rewards, penalties = get_attestation_deltas(cfg, state, proc)
    balances = np.array(state.balances, dtype=np.int64)
    balances = np.maximum(0, balances + rewards - penalties)
    # bulk write-back: a slice assignment costs ONE incremental-tree
    # rebuild of the balances subtree (~25 ms native at 250k) instead of
    # 250k tracked per-index writes (~1.2 s of Python)
    state.balances[:] = balances.tolist()
    proc.balances = balances


# ---------------------------------------------------------------------------
# registry / slashings / final updates
# ---------------------------------------------------------------------------


def process_registry_updates(cfg, state, proc: EpochProcess, epoch_ctx: EpochContext) -> None:
    epoch = proc.current_epoch
    # eligibility + ejection
    for i, v in enumerate(state.validators):
        if (
            v.activation_eligibility_epoch == FAR_FUTURE_EPOCH
            and v.effective_balance == _p.MAX_EFFECTIVE_BALANCE
        ):
            v = state.validators[i] = v.replace(
                activation_eligibility_epoch=epoch + 1
            )
        if (
            proc.is_active_curr[i]
            and v.effective_balance <= cfg.EJECTION_BALANCE
        ):
            from ..block.phase0 import initiate_validator_exit

            initiate_validator_exit(cfg, state, epoch_ctx, i)
    # dequeue activations up to churn limit
    queue = sorted(
        (
            i
            for i, v in enumerate(state.validators)
            if v.activation_eligibility_epoch <= state.finalized_checkpoint.epoch
            and v.activation_epoch == FAR_FUTURE_EPOCH
        ),
        key=lambda i: (state.validators[i].activation_eligibility_epoch, i),
    )
    churn = get_validator_churn_limit(cfg, int(proc.is_active_curr.sum()))
    for i in queue[:churn]:
        state.validators[i] = state.validators[i].replace(
            activation_epoch=compute_activation_exit_epoch(epoch)
        )


def process_slashings(cfg, state, proc: EpochProcess) -> None:
    epoch = proc.current_epoch
    total_balance = proc.total_active_balance
    total_slashings = sum(state.slashings)
    mult = min(
        total_slashings * _p.PROPORTIONAL_SLASHING_MULTIPLIER, total_balance
    )
    for i, v in enumerate(state.validators):
        if (
            v.slashed
            and epoch + _p.EPOCHS_PER_SLASHINGS_VECTOR // 2 == v.withdrawable_epoch
        ):
            increment = _p.EFFECTIVE_BALANCE_INCREMENT
            penalty_numerator = v.effective_balance // increment * mult
            penalty = penalty_numerator // total_balance * increment
            state.balances[i] = max(0, state.balances[i] - penalty)


def process_eth1_data_reset(cfg, state, proc: EpochProcess) -> None:
    next_epoch = proc.current_epoch + 1
    if next_epoch % _p.EPOCHS_PER_ETH1_VOTING_PERIOD == 0:
        state.eth1_data_votes = []


def process_effective_balance_updates(cfg, state, proc: EpochProcess) -> None:
    increment = _p.EFFECTIVE_BALANCE_INCREMENT
    hysteresis = increment // _p.HYSTERESIS_QUOTIENT
    down = hysteresis * _p.HYSTERESIS_DOWNWARD_MULTIPLIER
    up = hysteresis * _p.HYSTERESIS_UPWARD_MULTIPLIER
    for i, v in enumerate(state.validators):
        balance = state.balances[i]
        if (
            balance + down < v.effective_balance
            or v.effective_balance + up < balance
        ):
            state.validators[i] = v.replace(
                effective_balance=min(
                    balance - balance % increment, _p.MAX_EFFECTIVE_BALANCE
                )
            )


def process_slashings_reset(cfg, state, proc: EpochProcess) -> None:
    next_epoch = proc.current_epoch + 1
    state.slashings[next_epoch % _p.EPOCHS_PER_SLASHINGS_VECTOR] = 0


def process_randao_mixes_reset(cfg, state, proc: EpochProcess) -> None:
    next_epoch = proc.current_epoch + 1
    state.randao_mixes[next_epoch % _p.EPOCHS_PER_HISTORICAL_VECTOR] = get_randao_mix(
        state, proc.current_epoch
    )


def process_historical_roots_update(cfg, state, proc: EpochProcess) -> None:
    next_epoch = proc.current_epoch + 1
    if (
        next_epoch
        % (_p.SLOTS_PER_HISTORICAL_ROOT // _p.SLOTS_PER_EPOCH)
        == 0
    ):
        batch = ssz.phase0.HistoricalBatch(
            block_roots=list(state.block_roots),
            state_roots=list(state.state_roots),
        )
        state.historical_roots.append(
            ssz.phase0.HistoricalBatch.hash_tree_root(batch)
        )


def process_participation_record_updates(cfg, state, proc: EpochProcess) -> None:
    state.previous_epoch_attestations = state.current_epoch_attestations
    state.current_epoch_attestations = []


def process_epoch(cfg, state, epoch_ctx: EpochContext) -> EpochProcess:
    proc = before_process_epoch(cfg, state, epoch_ctx)
    process_justification_and_finalization(cfg, state, proc)
    process_rewards_and_penalties(cfg, state, proc)
    process_registry_updates(cfg, state, proc, epoch_ctx)
    process_slashings(cfg, state, proc)
    process_eth1_data_reset(cfg, state, proc)
    process_effective_balance_updates(cfg, state, proc)
    process_slashings_reset(cfg, state, proc)
    process_randao_mixes_reset(cfg, state, proc)
    process_historical_roots_update(cfg, state, proc)
    process_participation_record_updates(cfg, state, proc)
    return proc
