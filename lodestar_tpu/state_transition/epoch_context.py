"""Per-epoch flat caches: shufflings, committees, proposers, balances.

The rebuild's EpochContext (reference:
packages/state-transition/src/cache/epochContext.ts:80,
util/epochShuffling.ts, cache/effectiveBalanceIncrements.ts): everything
O(V) is precomputed once per epoch into numpy arrays — the representation
both the host hot loops and future device kernels consume directly
(SURVEY §2.4 rebuild note).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Tuple

import numpy as np

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
)
from .util.misc import (
    compute_committee_count_per_slot,
    compute_epoch_at_slot,
    compute_proposer_index,
    compute_start_slot_at_epoch,
    get_seed,
    int_to_bytes,
    sha256,
    shuffle_list,
)


@dataclass
class EpochShuffling:
    """Shuffling of one epoch's active set (util/epochShuffling.ts)."""

    epoch: int
    active_indices: np.ndarray  # all active validator indices
    shuffling: np.ndarray       # shuffled active indices (flat)
    committees_per_slot: int

    def committee(self, slot: int, index: int) -> np.ndarray:
        """Committee = contiguous slice of the shuffled list (spec
        compute_committee)."""
        slot_in_epoch = slot % _p.SLOTS_PER_EPOCH
        committee_index = slot_in_epoch * self.committees_per_slot + index
        count = self.committees_per_slot * _p.SLOTS_PER_EPOCH
        n = len(self.shuffling)
        start = n * committee_index // count
        end = n * (committee_index + 1) // count
        return self.shuffling[start:end]


def compute_epoch_shuffling(state, epoch: int) -> EpochShuffling:
    active = np.array(
        [
            i
            for i, v in enumerate(state.validators)
            if v.activation_epoch <= epoch < v.exit_epoch
        ],
        dtype=np.int64,
    )
    seed = get_seed(state, epoch, DOMAIN_BEACON_ATTESTER)
    shuffled = shuffle_list(active, seed)
    return EpochShuffling(
        epoch=epoch,
        active_indices=active,
        shuffling=shuffled,
        committees_per_slot=compute_committee_count_per_slot(len(active)),
    )


class EpochContext:
    """Caches for the CURRENT state epoch plus previous/next shufflings,
    rebuilt/rotated on epoch transitions."""

    def __init__(self, state):
        self.pubkey2index: Dict[bytes, int] = {
            bytes(v.pubkey): i for i, v in enumerate(state.validators)
        }
        epoch = compute_epoch_at_slot(state.slot)
        self.epoch = epoch
        self.previous_shuffling = compute_epoch_shuffling(state, max(0, epoch - 1))
        self.current_shuffling = compute_epoch_shuffling(state, epoch)
        self.next_shuffling = compute_epoch_shuffling(state, epoch + 1)
        self.effective_balance_increments = np.array(
            [v.effective_balance // _p.EFFECTIVE_BALANCE_INCREMENT for v in state.validators],
            dtype=np.int64,
        )
        self.proposers = self._compute_proposers(state, epoch)
        # exit-queue cache (reference epochContext exitQueueEpoch/Churn),
        # computed lazily by initiate_validator_exit, updated incrementally
        self.exit_queue_epoch: Optional[int] = None
        self.exit_queue_churn = 0
        self.churn_limit = 0

    def clone(self) -> "EpochContext":
        """Copy for a forked state: immutable caches (numpy shufflings,
        proposers) are shared; mutable per-fork state (pubkey2index, exit
        queue) is copied."""
        import copy as _copy

        new = _copy.copy(self)
        new.pubkey2index = dict(self.pubkey2index)
        return new

    # ------------------------------------------------------------------

    def _compute_proposers(self, state, epoch: int) -> List[int]:
        eff = self.effective_balance_increments * _p.EFFECTIVE_BALANCE_INCREMENT
        out = []
        active = self.current_shuffling.active_indices
        base_seed = get_seed(state, epoch, DOMAIN_BEACON_PROPOSER)
        for slot in range(
            compute_start_slot_at_epoch(epoch),
            compute_start_slot_at_epoch(epoch + 1),
        ):
            seed = sha256(base_seed + int_to_bytes(slot, 8))
            out.append(compute_proposer_index(eff, active, seed))
        return out

    def get_beacon_proposer(self, slot: int) -> int:
        epoch = compute_epoch_at_slot(slot)
        assert epoch == self.epoch, f"proposer requested for epoch {epoch} != {self.epoch}"
        return self.proposers[slot % _p.SLOTS_PER_EPOCH]

    def get_shuffling(self, epoch: int) -> EpochShuffling:
        if epoch == self.epoch:
            return self.current_shuffling
        if epoch == self.epoch - 1:
            return self.previous_shuffling
        if epoch == self.epoch + 1:
            return self.next_shuffling
        raise ValueError(f"no shuffling cached for epoch {epoch} (at {self.epoch})")

    def get_committee(self, slot: int, index: int) -> np.ndarray:
        return self.get_shuffling(compute_epoch_at_slot(slot)).committee(slot, index)

    def get_committee_count_per_slot(self, epoch: int) -> int:
        return self.get_shuffling(epoch).committees_per_slot

    def total_active_balance_increments(self, epoch: Optional[int] = None) -> int:
        sh = self.get_shuffling(self.epoch if epoch is None else epoch)
        if len(sh.active_indices) == 0:
            return 1
        return max(1, int(self.effective_balance_increments[sh.active_indices].sum()))

    # epoch rollover ---------------------------------------------------

    def rotate(self, state) -> None:
        """After an epoch transition: shift shufflings and rebuild the
        epoch-scoped caches (epochContext.ts afterProcessEpoch)."""
        new_epoch = compute_epoch_at_slot(state.slot)
        assert new_epoch == self.epoch + 1
        self.previous_shuffling = self.current_shuffling
        self.current_shuffling = self.next_shuffling
        self.next_shuffling = compute_epoch_shuffling(state, new_epoch + 1)
        self.epoch = new_epoch
        self.effective_balance_increments = np.array(
            [v.effective_balance // _p.EFFECTIVE_BALANCE_INCREMENT for v in state.validators],
            dtype=np.int64,
        )
        self.proposers = self._compute_proposers(state, new_epoch)
        self.exit_queue_epoch = None  # recompute lazily for the new epoch
        self.exit_queue_churn = 0
        self.churn_limit = 0
        for i, v in enumerate(state.validators):
            pk = bytes(v.pubkey)
            if pk not in self.pubkey2index:
                self.pubkey2index[pk] = i
