"""Domain & signing-root helpers (reference:
packages/state-transition/src/util/domain.ts and signingRoot.ts).
"""
from __future__ import annotations

from lodestar_tpu.types import ssz

ZERO_HASH = b"\x00" * 32


def compute_fork_data_root(current_version: bytes, genesis_validators_root: bytes) -> bytes:
    fd = ssz.phase0.ForkData(
        current_version=current_version,
        genesis_validators_root=genesis_validators_root,
    )
    return ssz.phase0.ForkData.hash_tree_root(fd)


def compute_domain(
    domain_type: bytes,
    fork_version: bytes,
    genesis_validators_root: bytes = ZERO_HASH,
) -> bytes:
    fork_data_root = compute_fork_data_root(fork_version, genesis_validators_root)
    return domain_type + fork_data_root[:28]


def compute_signing_root(ssz_type, obj, domain: bytes) -> bytes:
    sd = ssz.phase0.SigningData(
        object_root=ssz_type.hash_tree_root(obj), domain=domain
    )
    return ssz.phase0.SigningData.hash_tree_root(sd)
