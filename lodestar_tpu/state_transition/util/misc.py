"""Core spec helpers: epochs, balances, randao, seeds, shuffling, committees.

Mirrors packages/state-transition/src/util/{epoch,validator,seed,shuffle,
balance,blockRoot}.ts.  The full-list shuffling is vectorized with numpy —
the flat-array representation the reference computes once per epoch in its
EpochContext (cache/epochShuffling.ts) and exactly the layout a TPU kernel
wants.
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Sequence

import numpy as np

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DOMAIN_BEACON_PROPOSER,
    FAR_FUTURE_EPOCH,
    GENESIS_EPOCH,
)

SLOTS_PER_EPOCH = _p.SLOTS_PER_EPOCH


def sha256(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()


def int_to_bytes(n: int, length: int) -> bytes:
    return int(n).to_bytes(length, "little")


def compute_epoch_at_slot(slot: int) -> int:
    return slot // SLOTS_PER_EPOCH


def compute_start_slot_at_epoch(epoch: int) -> int:
    return epoch * SLOTS_PER_EPOCH


def compute_activation_exit_epoch(epoch: int) -> int:
    return epoch + 1 + _p.MAX_SEED_LOOKAHEAD


def is_active_validator(validator, epoch: int) -> bool:
    return validator.activation_epoch <= epoch < validator.exit_epoch


def get_active_validator_indices(state, epoch: int) -> List[int]:
    return [
        i for i, v in enumerate(state.validators) if is_active_validator(v, epoch)
    ]


def get_validator_churn_limit(cfg, active_count: int) -> int:
    return max(cfg.MIN_PER_EPOCH_CHURN_LIMIT, active_count // cfg.CHURN_LIMIT_QUOTIENT)


def increase_balance(state, index: int, delta: int) -> None:
    state.balances[index] += delta


def decrease_balance(state, index: int, delta: int) -> None:
    state.balances[index] = max(0, state.balances[index] - delta)


def get_randao_mix(state, epoch: int) -> bytes:
    return state.randao_mixes[epoch % _p.EPOCHS_PER_HISTORICAL_VECTOR]


def get_block_root_at_slot(state, slot: int) -> bytes:
    if not (slot < state.slot <= slot + _p.SLOTS_PER_HISTORICAL_ROOT):
        raise ValueError(f"slot {slot} out of block_roots range at {state.slot}")
    return state.block_roots[slot % _p.SLOTS_PER_HISTORICAL_ROOT]


def get_block_root(state, epoch: int) -> bytes:
    return get_block_root_at_slot(state, compute_start_slot_at_epoch(epoch))


def get_seed(state, epoch: int, domain_type: bytes) -> bytes:
    mix = get_randao_mix(
        state, epoch + _p.EPOCHS_PER_HISTORICAL_VECTOR - _p.MIN_SEED_LOOKAHEAD - 1
    )
    return sha256(domain_type + int_to_bytes(epoch, 8) + mix)


# ---------------------------------------------------------------------------
# swap-or-not shuffling (spec compute_shuffled_index + vectorized full list)
# ---------------------------------------------------------------------------


def compute_shuffled_index(index: int, count: int, seed: bytes) -> int:
    """Scalar spec shuffling of one index (forward permutation)."""
    assert index < count
    for round_ in range(_p.SHUFFLE_ROUND_COUNT):
        pivot = (
            int.from_bytes(sha256(seed + bytes([round_]))[:8], "little") % count
        )
        flip = (pivot + count - index) % count
        position = max(index, flip)
        source = sha256(seed + bytes([round_]) + int_to_bytes(position // 256, 4))
        byte = source[(position % 256) // 8]
        bit = (byte >> (position % 8)) & 1
        index = flip if bit else index
    return index


def compute_shuffled_indices_vec(count: int, seed: bytes) -> np.ndarray:
    """compute_shuffled_index applied to every position at once (numpy).

    Each swap-or-not round is an elementwise involution, so running the
    scalar update rule over the whole positions array yields the forward
    map f for all positions simultaneously.  This is the flat epoch-cache
    layout the reference computes in cache/epochShuffling.ts, vectorized.
    """
    positions = np.arange(count, dtype=np.int64)
    if count == 0:
        return positions
    nblocks = (count + 255) // 256
    for round_ in range(_p.SHUFFLE_ROUND_COUNT):
        pivot = (
            int.from_bytes(sha256(seed + bytes([round_]))[:8], "little") % count
        )
        flip = (pivot - positions) % count
        pos_max = np.maximum(positions, flip)
        srcs = np.frombuffer(
            b"".join(
                sha256(seed + bytes([round_]) + int_to_bytes(b, 4))
                for b in range(nblocks)
            ),
            dtype=np.uint8,
        )
        byte = srcs[pos_max // 8]
        bit = (byte >> (pos_max % 8).astype(np.uint8)) & 1
        positions = np.where(bit == 1, flip, positions)
    return positions


def shuffle_list(indices: np.ndarray, seed: bytes) -> np.ndarray:
    """Full shuffled list L with L[pos] = indices[f(pos)] — the committee
    layout consumed by compute_committee."""
    return np.asarray(indices)[compute_shuffled_indices_vec(len(indices), seed)]


def compute_proposer_index(
    effective_balances: Sequence[int], indices: Sequence[int], seed: bytes
) -> int:
    """Spec compute_proposer_index over active `indices` with a flat
    effective-balance array (reference epochContext computeProposers)."""
    assert len(indices) > 0
    MAX_RANDOM_BYTE = 255
    n = len(indices)
    i = 0
    while True:
        candidate = indices[compute_shuffled_index(i % n, n, seed)]
        random_byte = sha256(seed + int_to_bytes(i // 32, 8))[i % 32]
        if (
            effective_balances[candidate] * MAX_RANDOM_BYTE
            >= _p.MAX_EFFECTIVE_BALANCE * random_byte
        ):
            return candidate
        i += 1


def compute_committee_count_per_slot(active_count: int) -> int:
    return max(
        1,
        min(
            _p.MAX_COMMITTEES_PER_SLOT,
            active_count // SLOTS_PER_EPOCH // _p.TARGET_COMMITTEE_SIZE,
        ),
    )
