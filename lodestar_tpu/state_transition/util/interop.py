"""Interop (devnet) deterministic keys (reference:
packages/state-transition/src/util/interop.ts; eth2.0-pm interop spec).

sk_i = int_LE(sha256(uint256_LE(i))) mod r
"""
from __future__ import annotations

import hashlib
from typing import List

from lodestar_tpu.crypto.bls.api import SecretKey
from lodestar_tpu.crypto.bls.fields import R as CURVE_ORDER


def interop_secret_key(index: int) -> SecretKey:
    h = hashlib.sha256(index.to_bytes(32, "little")).digest()
    sk = int.from_bytes(h, "little") % CURVE_ORDER
    return SecretKey(sk)


def interop_secret_keys(count: int) -> List[SecretKey]:
    return [interop_secret_key(i) for i in range(count)]
