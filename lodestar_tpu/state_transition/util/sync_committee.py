"""Sync committee computation (reference:
packages/state-transition/src/util/syncCommittee.ts getNextSyncCommittee;
consensus-specs altair).
"""
from __future__ import annotations

from typing import List, Sequence

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import ACTIVE_PRESET as _p, DOMAIN_SYNC_COMMITTEE
from lodestar_tpu.types import ssz
from .misc import (
    compute_epoch_at_slot,
    compute_shuffled_index,
    get_seed,
    int_to_bytes,
    sha256,
)

MAX_RANDOM_BYTE = 255


def get_next_sync_committee_indices(state, active_indices: Sequence[int],
                                    effective_balances: Sequence[int]) -> List[int]:
    """Spec get_next_sync_committee_indices: balance-weighted sampling over
    the shuffled active set at epoch+1."""
    epoch = compute_epoch_at_slot(state.slot) + 1
    seed = get_seed(state, epoch, DOMAIN_SYNC_COMMITTEE)
    n = len(active_indices)
    out: List[int] = []
    i = 0
    while len(out) < _p.SYNC_COMMITTEE_SIZE:
        shuffled = compute_shuffled_index(i % n, n, seed)
        candidate = int(active_indices[shuffled])
        random_byte = sha256(seed + int_to_bytes(i // 32, 8))[i % 32]
        if effective_balances[candidate] * MAX_RANDOM_BYTE >= (
            _p.MAX_EFFECTIVE_BALANCE * random_byte
        ):
            out.append(candidate)
        i += 1
    return out


def get_next_sync_committee(state, active_indices, effective_balances):
    indices = get_next_sync_committee_indices(state, active_indices, effective_balances)
    pubkeys = [bytes(state.validators[i].pubkey) for i in indices]
    agg = bls.aggregate_public_keys(
        [bls.PublicKey.from_bytes(pk) for pk in pubkeys]
    )
    committee = ssz.altair.SyncCommittee(
        pubkeys=pubkeys, aggregate_pubkey=agg.to_bytes()
    )
    return committee, indices
