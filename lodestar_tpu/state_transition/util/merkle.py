"""Merkle branch verification + single-proof generation for SSZ List trees.

Reference: @lodestar/utils verifyMerkleBranch and
@chainsafe/persistent-merkle-tree getSingleProof (used by the deposit tree,
beacon-node/src/node/utils/interop/deposits.ts).
"""
from __future__ import annotations

from typing import List, Sequence

from lodestar_tpu.ssz.core import ZERO_HASHES, hash_nodes


def is_valid_merkle_branch(
    leaf: bytes, branch: Sequence[bytes], depth: int, index: int, root: bytes
) -> bool:
    value = leaf
    for i in range(depth):
        if (index >> i) & 1:
            value = hash_nodes(branch[i], value)
        else:
            value = hash_nodes(value, branch[i])
    return value == root


def list_tree_layers(leaves: Sequence[bytes], depth: int) -> List[List[bytes]]:
    """Bottom-up layers of a depth-`depth` padded tree over `leaves`."""
    layers = [[bytes(leaf) for leaf in leaves]]
    for level in range(depth):
        prev = layers[-1]
        nxt = []
        for i in range(0, len(prev) - 1, 2):
            nxt.append(hash_nodes(prev[i], prev[i + 1]))
        if len(prev) % 2:
            nxt.append(hash_nodes(prev[-1], ZERO_HASHES[level]))
        layers.append(nxt)
    return layers


def list_single_proof(
    leaves: Sequence[bytes], depth: int, index: int, length: int
) -> List[bytes]:
    """Proof for leaf `index` of an SSZ List[Root, 2**depth] tree: `depth`
    sibling hashes bottom-up plus the mix-in-length chunk (the shape of the
    reference's deposit proof fixture)."""
    layers = list_tree_layers(leaves, depth)
    proof = []
    idx = index
    for level in range(depth):
        sib = idx ^ 1
        layer = layers[level]
        proof.append(layer[sib] if sib < len(layer) else ZERO_HASHES[level])
        idx >>= 1
    proof.append(int(length).to_bytes(32, "little"))
    return proof


def list_tree_root(leaves: Sequence[bytes], depth: int, length: int) -> bytes:
    layers = list_tree_layers(leaves, depth)
    top = layers[depth][0] if layers[depth] else ZERO_HASHES[depth]
    return hash_nodes(top, int(length).to_bytes(32, "little"))
