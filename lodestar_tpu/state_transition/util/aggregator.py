"""Aggregator selection (reference:
packages/state-transition/src/util/aggregator.ts, validated by the
reference's aggregator.test.ts fixtures).

is_aggregator: hash(slot_signature)[0:8] as LE uint64 modulo
(committee_size // TARGET_AGGREGATORS_PER_COMMITTEE) == 0.
"""
from __future__ import annotations

import hashlib

from lodestar_tpu.params import (
    SYNC_COMMITTEE_SIZE,
    SYNC_COMMITTEE_SUBNET_COUNT,
    TARGET_AGGREGATORS_PER_COMMITTEE,
    TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
)


def _is_selection_proof_valid(sig_bytes: bytes, modulo: int) -> bool:
    digest = hashlib.sha256(sig_bytes).digest()
    return int.from_bytes(digest[0:8], "little") % modulo == 0


def is_aggregator_from_committee_length(committee_length: int, slot_signature: bytes) -> bool:
    modulo = max(1, committee_length // TARGET_AGGREGATORS_PER_COMMITTEE)
    return _is_selection_proof_valid(slot_signature, modulo)


def is_sync_committee_aggregator(selection_proof: bytes) -> bool:
    modulo = max(
        1,
        SYNC_COMMITTEE_SIZE
        // SYNC_COMMITTEE_SUBNET_COUNT
        // TARGET_AGGREGATORS_PER_SYNC_SUBCOMMITTEE,
    )
    return _is_selection_proof_valid(selection_proof, modulo)
