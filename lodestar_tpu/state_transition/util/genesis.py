"""Genesis state construction (reference:
packages/state-transition/src/util/genesis.ts and the interop dev-state
builders, beacon-node/src/node/utils/{state.ts,interop/}).
"""
from __future__ import annotations

import hashlib
from typing import List, Optional, Tuple

from lodestar_tpu.config import ChainConfig
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    BLS_WITHDRAWAL_PREFIX,
    DEPOSIT_CONTRACT_TREE_DEPTH,
    DOMAIN_DEPOSIT,
    GENESIS_EPOCH,
    GENESIS_SLOT,
    ForkName,
)
from lodestar_tpu.types import ssz
from ..block.process_deposit import process_deposit
from .domain import ZERO_HASH, compute_domain, compute_signing_root
from .interop import interop_secret_keys
from .merkle import list_single_proof, list_tree_root
from .misc import compute_epoch_at_slot, get_active_validator_indices


def get_temporary_block_header() -> "ssz.phase0.BeaconBlockHeader":
    """Header of the default genesis block (body_root of an empty body)."""
    body = ssz.phase0.BeaconBlockBody.default()
    return ssz.phase0.BeaconBlockHeader(
        slot=GENESIS_SLOT,
        proposer_index=0,
        parent_root=ZERO_HASH,
        state_root=ZERO_HASH,
        body_root=ssz.phase0.BeaconBlockBody.hash_tree_root(body),
    )


def get_genesis_beacon_state(cfg: ChainConfig) -> "ssz.phase0.BeaconState":
    state = ssz.phase0.BeaconState.default()
    state.slot = GENESIS_SLOT
    state.fork = ssz.phase0.Fork(
        previous_version=cfg.GENESIS_FORK_VERSION,
        current_version=cfg.GENESIS_FORK_VERSION,
        epoch=GENESIS_EPOCH,
    )
    state.latest_block_header = get_temporary_block_header()
    return state


def apply_deposits(
    cfg: ChainConfig, state, deposits, deposit_data_roots: Optional[List[bytes]] = None
) -> int:
    """Genesis deposit application: incrementally advance
    eth1_data.deposit_root then process each deposit; finish with balance/
    activation sweep and genesis_validators_root (genesis.ts applyDeposits)."""
    roots = deposit_data_roots or [
        ssz.phase0.DepositData.hash_tree_root(d.data) for d in deposits
    ]
    pubkey2index = {
        bytes(v.pubkey): i for i, v in enumerate(state.validators)
    }
    for i, deposit in enumerate(deposits):
        state.eth1_data.deposit_root = list_tree_root(
            roots[: i + 1], DEPOSIT_CONTRACT_TREE_DEPTH, i + 1
        )
        state.eth1_data.deposit_count += 1
        process_deposit(ForkName.phase0, cfg, state, deposit, pubkey2index)

    activated = 0
    for i, v in enumerate(state.validators):
        if v.activation_epoch == GENESIS_EPOCH:
            continue
        balance = state.balances[i]
        eff = min(
            balance - balance % _p.EFFECTIVE_BALANCE_INCREMENT,
            _p.MAX_EFFECTIVE_BALANCE,
        )
        kw = {"effective_balance": eff}
        if eff == _p.MAX_EFFECTIVE_BALANCE:
            kw["activation_eligibility_epoch"] = GENESIS_EPOCH
            kw["activation_epoch"] = GENESIS_EPOCH
            activated += 1
        state.validators[i] = v.replace(**kw)

    validators_t = ssz.phase0.BeaconState._fields_["validators"]
    state.genesis_validators_root = validators_t.hash_tree_root(state.validators)
    return activated


def initialize_beacon_state_from_eth1(
    cfg: ChainConfig,
    eth1_block_hash: bytes,
    eth1_timestamp: int,
    deposits,
    deposit_data_roots: Optional[List[bytes]] = None,
):
    state = get_genesis_beacon_state(cfg)
    state.genesis_time = eth1_timestamp + cfg.GENESIS_DELAY
    state.eth1_data.block_hash = eth1_block_hash
    state.randao_mixes = [eth1_block_hash] * _p.EPOCHS_PER_HISTORICAL_VECTOR
    apply_deposits(cfg, state, deposits, deposit_data_roots)
    return state


def is_valid_genesis_state(cfg: ChainConfig, state) -> bool:
    if state.genesis_time < cfg.MIN_GENESIS_TIME:
        return False
    active = get_active_validator_indices(state, compute_epoch_at_slot(GENESIS_SLOT))
    return len(active) >= cfg.MIN_GENESIS_ACTIVE_VALIDATOR_COUNT


# ---------------------------------------------------------------------------
# interop / dev chain builders (beacon-node/src/node/utils/interop/)
# ---------------------------------------------------------------------------


def interop_deposits(
    cfg: ChainConfig, count: int, with_eth1_credentials: bool = False
) -> List["ssz.phase0.Deposit"]:
    """Deterministic dev deposits; proof generated from the incremental
    deposit tree exactly like interop/deposits.ts (tree contains leaves
    0..i when proving leaf i)."""
    sks = interop_secret_keys(count)
    roots: List[bytes] = []
    deposits = []
    prefix = 1 if with_eth1_credentials else BLS_WITHDRAWAL_PREFIX
    for i, sk in enumerate(sks):
        pubkey = sk.to_public_key().to_bytes()
        wc = bytearray(hashlib.sha256(pubkey).digest())
        wc[0] = prefix
        data = ssz.phase0.DepositData(
            pubkey=pubkey,
            withdrawal_credentials=bytes(wc),
            amount=_p.MAX_EFFECTIVE_BALANCE,
            signature=b"\x00" * 96,
        )
        dm = ssz.phase0.DepositMessage(
            pubkey=pubkey, withdrawal_credentials=bytes(wc), amount=data.amount
        )
        domain = compute_domain(DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION, ZERO_HASH)
        data.signature = sk.sign(
            compute_signing_root(ssz.phase0.DepositMessage, dm, domain)
        ).to_bytes()
        roots.append(ssz.phase0.DepositData.hash_tree_root(data))
        proof = list_single_proof(roots, DEPOSIT_CONTRACT_TREE_DEPTH, i, i + 1)
        deposits.append(ssz.phase0.Deposit(proof=proof, data=data))
    return deposits


def init_dev_state(
    cfg: ChainConfig,
    validator_count: int,
    genesis_time: Optional[int] = None,
    eth1_block_hash: bytes = b"B" * 32,
    eth1_timestamp: int = 2**40,
) -> Tuple[List["ssz.phase0.Deposit"], "ssz.phase0.BeaconState"]:
    """initDevState (beacon-node/src/node/utils/state.ts): interop deposits
    + genesis state with overridable genesis time."""
    deposits = interop_deposits(cfg, validator_count)
    state = initialize_beacon_state_from_eth1(
        cfg, eth1_block_hash, eth1_timestamp, deposits
    )
    if genesis_time is not None:
        state.genesis_time = genesis_time
    # fork-at-genesis dev nets: upgrade the phase0 genesis in place through
    # every fork scheduled at epoch 0 (the reference's getGenesisBeaconState
    # upgrades per fork schedule)
    if cfg.ALTAIR_FORK_EPOCH == 0:
        from ..epoch_context import EpochContext
        from .. import upgrade as upg

        state = upg.upgrade_to_altair(cfg, state, EpochContext(state))
        state.fork.previous_version = cfg.GENESIS_FORK_VERSION
        if cfg.BELLATRIX_FORK_EPOCH == 0:
            state = upg.upgrade_to_bellatrix(cfg, state, None)
            state.fork.previous_version = cfg.GENESIS_FORK_VERSION
            # post-merge-from-genesis: a non-default genesis execution
            # header so is_merge_transition_complete is true from slot 0
            # (reference node/utils/interop/state.ts executionPayloadHeader)
            state.latest_execution_payload_header.block_hash = eth1_block_hash
            state.latest_execution_payload_header.timestamp = state.genesis_time
            state.latest_execution_payload_header.prev_randao = eth1_block_hash
            if cfg.CAPELLA_FORK_EPOCH == 0:
                state = upg.upgrade_to_capella(cfg, state, None)
                state.fork.previous_version = cfg.GENESIS_FORK_VERSION
                if cfg.EIP4844_FORK_EPOCH == 0:
                    state = upg.upgrade_to_eip4844(cfg, state, None)
                    state.fork.previous_version = cfg.GENESIS_FORK_VERSION
    return deposits, state
