"""Per-fork penalty/reward constants (consensus-specs altair & bellatrix
beacon-chain.md "Updated ... quotients"; reference keeps these switches
inline in state-transition/src/{block/slashValidator.ts,epoch/*}).
"""
from __future__ import annotations

from lodestar_tpu.params import ACTIVE_PRESET as _p, FORK_SEQ, ForkName


def min_slashing_penalty_quotient(fork: ForkName) -> int:
    if fork is ForkName.phase0:
        return _p.MIN_SLASHING_PENALTY_QUOTIENT
    if fork is ForkName.altair:
        return _p.MIN_SLASHING_PENALTY_QUOTIENT_ALTAIR
    return _p.MIN_SLASHING_PENALTY_QUOTIENT_BELLATRIX


def proportional_slashing_multiplier(fork: ForkName) -> int:
    if fork is ForkName.phase0:
        return _p.PROPORTIONAL_SLASHING_MULTIPLIER
    if fork is ForkName.altair:
        return _p.PROPORTIONAL_SLASHING_MULTIPLIER_ALTAIR
    return _p.PROPORTIONAL_SLASHING_MULTIPLIER_BELLATRIX


def inactivity_penalty_quotient(fork: ForkName) -> int:
    if fork is ForkName.phase0:
        return _p.INACTIVITY_PENALTY_QUOTIENT
    if fork is ForkName.altair:
        return _p.INACTIVITY_PENALTY_QUOTIENT_ALTAIR
    return _p.INACTIVITY_PENALTY_QUOTIENT_BELLATRIX


def is_post_fork(fork: ForkName, base: ForkName) -> bool:
    return FORK_SEQ[fork] >= FORK_SEQ[base]
