"""The state transition function (reference:
packages/state-transition/src/stateTransition.ts:42).

process_slots advances through empty slots (epoch processing at
boundaries), process_block applies a block, state_transition does both plus
the optional post-state root check.  Signature verification is decoupled:
callers run the BLS sets through the device verifier in parallel
(chain/blocks/verifyBlock.ts:71-80 pattern).
"""
from __future__ import annotations

from typing import Optional, Tuple

from lodestar_tpu.params import ACTIVE_PRESET as _p, FORK_SEQ, ForkName
from lodestar_tpu.types import fork_of_block, fork_of_state, ssz, types_for
from .block import (
    altair as block_altair,
    bellatrix as block_bellatrix,
    capella as block_capella,
    eip4844 as block_eip4844,
    phase0 as block_phase0,
)
from .epoch import altair as epoch_altair, phase0 as epoch_phase0
from .epoch_context import EpochContext
from .util.misc import compute_epoch_at_slot

# per-fork processor dispatch (the reference's allForks indirection,
# state-transition/src/stateTransition.ts processBlock/processEpoch switch).
# The altair epoch module is fork-aware from altair onward (quotients +
# historical-summaries switch keyed on the state's fork).
_PROCESSORS = {
    ForkName.phase0: (block_phase0, epoch_phase0),
    ForkName.altair: (block_altair, epoch_altair),
    ForkName.bellatrix: (block_bellatrix, epoch_altair),
    ForkName.capella: (block_capella, epoch_altair),
    ForkName.eip4844: (block_eip4844, epoch_altair),
}


def processors_for(state):
    return _PROCESSORS[fork_of_state(state)]


def state_hash_tree_root(state) -> bytes:
    return type(state).hash_tree_root(state)


class CachedBeaconState:
    """State + epoch caches travelling together (the reference's
    CachedBeaconState, cache/stateCache.ts:127 — here a thin pair since the
    flat caches live in EpochContext)."""

    def __init__(self, cfg, state, epoch_ctx: Optional[EpochContext] = None):
        self.cfg = cfg
        self.state = state
        self.epoch_ctx = epoch_ctx or EpochContext(state)

    def clone(self) -> "CachedBeaconState":
        new = CachedBeaconState.__new__(CachedBeaconState)
        new.cfg = self.cfg
        new.state = self.state.copy()
        new.epoch_ctx = self.epoch_ctx.clone()
        return new

    def hash_tree_root(self) -> bytes:
        return state_hash_tree_root(self.state)


def process_slot(cfg, state) -> None:
    """Cache state/block roots for the slot about to end."""
    prev_state_root = state_hash_tree_root(state)
    state.state_roots[state.slot % _p.SLOTS_PER_HISTORICAL_ROOT] = prev_state_root
    if bytes(state.latest_block_header.state_root) == b"\x00" * 32:
        state.latest_block_header.state_root = prev_state_root
    block_root = ssz.phase0.BeaconBlockHeader.hash_tree_root(
        state.latest_block_header
    )
    state.block_roots[state.slot % _p.SLOTS_PER_HISTORICAL_ROOT] = block_root


def process_slots(cached: CachedBeaconState, slot: int) -> None:
    state = cached.state
    if state.slot >= slot:
        raise ValueError(f"cannot advance state from {state.slot} to {slot}")
    while state.slot < slot:
        process_slot(cached.cfg, state)
        if (state.slot + 1) % _p.SLOTS_PER_EPOCH == 0:
            _, epoch_mod = processors_for(state)
            epoch_mod.process_epoch(cached.cfg, state, cached.epoch_ctx)
            state.slot += 1
            cached.epoch_ctx.rotate(state)
            # fork upgrades at the boundary (stateTransition.ts processSlots
            # upgrade hooks) — applied in order so chained fork epochs work
            next_epoch = compute_epoch_at_slot(state.slot)
            from . import upgrade as upg

            for fork, epoch_attr, fn in (
                (ForkName.phase0, "ALTAIR_FORK_EPOCH", upg.upgrade_to_altair),
                (ForkName.altair, "BELLATRIX_FORK_EPOCH", upg.upgrade_to_bellatrix),
                (ForkName.bellatrix, "CAPELLA_FORK_EPOCH", upg.upgrade_to_capella),
                (ForkName.capella, "EIP4844_FORK_EPOCH", upg.upgrade_to_eip4844),
            ):
                if (
                    fork_of_state(state) is fork
                    and next_epoch == getattr(cached.cfg, epoch_attr)
                ):
                    cached.state = fn(cached.cfg, state, cached.epoch_ctx)
                    state = cached.state
        else:
            state.slot += 1


def state_transition(
    cached: CachedBeaconState,
    signed_block,
    verify_state_root: bool = True,
    verify_proposer: bool = True,
    verify_signatures: bool = True,
) -> CachedBeaconState:
    """Full STF on a CLONE of the input state; returns the post state."""
    post = cached.clone()
    block = signed_block.message
    if post.state.slot < block.slot:
        process_slots(post, block.slot)
    if verify_proposer:
        from .signature_sets import get_block_proposer_signature_set
        from lodestar_tpu.crypto.bls.api import verify_signature_set

        if not verify_signature_set(
            get_block_proposer_signature_set(post.cfg, post.state, post.epoch_ctx, signed_block)
        ):
            raise ValueError("invalid block signature")
    block_mod, _ = processors_for(post.state)
    if fork_of_block(block) is not fork_of_state(post.state):
        raise ValueError(
            f"block fork {fork_of_block(block)} != state fork {fork_of_state(post.state)}"
        )
    block_mod.process_block(
        post.cfg, post.state, post.epoch_ctx, block, verify_signatures
    )
    if verify_state_root:
        root = post.hash_tree_root()
        if bytes(block.state_root) != root:
            raise ValueError(
                f"state root mismatch: block {bytes(block.state_root).hex()} != {root.hex()}"
            )
    return post
