"""The state transition function (reference:
packages/state-transition/src/stateTransition.ts:42).

process_slots advances through empty slots (epoch processing at
boundaries), process_block applies a block, state_transition does both plus
the optional post-state root check.  Signature verification is decoupled:
callers run the BLS sets through the device verifier in parallel
(chain/blocks/verifyBlock.ts:71-80 pattern).
"""
from __future__ import annotations

from typing import Optional, Tuple

from lodestar_tpu.params import ACTIVE_PRESET as _p
from lodestar_tpu.types import ssz
from .block import phase0 as block_phase0
from .epoch import phase0 as epoch_phase0
from .epoch_context import EpochContext
from .util.misc import compute_epoch_at_slot


class CachedBeaconState:
    """State + epoch caches travelling together (the reference's
    CachedBeaconState, cache/stateCache.ts:127 — here a thin pair since the
    flat caches live in EpochContext)."""

    def __init__(self, cfg, state, epoch_ctx: Optional[EpochContext] = None):
        self.cfg = cfg
        self.state = state
        self.epoch_ctx = epoch_ctx or EpochContext(state)

    def clone(self) -> "CachedBeaconState":
        new = CachedBeaconState.__new__(CachedBeaconState)
        new.cfg = self.cfg
        new.state = self.state.copy()
        new.epoch_ctx = self.epoch_ctx.clone()
        return new

    def hash_tree_root(self) -> bytes:
        return ssz.phase0.BeaconState.hash_tree_root(self.state)


def process_slot(cfg, state) -> None:
    """Cache state/block roots for the slot about to end."""
    prev_state_root = ssz.phase0.BeaconState.hash_tree_root(state)
    state.state_roots[state.slot % _p.SLOTS_PER_HISTORICAL_ROOT] = prev_state_root
    if bytes(state.latest_block_header.state_root) == b"\x00" * 32:
        state.latest_block_header.state_root = prev_state_root
    block_root = ssz.phase0.BeaconBlockHeader.hash_tree_root(
        state.latest_block_header
    )
    state.block_roots[state.slot % _p.SLOTS_PER_HISTORICAL_ROOT] = block_root


def process_slots(cached: CachedBeaconState, slot: int) -> None:
    state = cached.state
    if state.slot >= slot:
        raise ValueError(f"cannot advance state from {state.slot} to {slot}")
    while state.slot < slot:
        process_slot(cached.cfg, state)
        if (state.slot + 1) % _p.SLOTS_PER_EPOCH == 0:
            epoch_phase0.process_epoch(cached.cfg, state, cached.epoch_ctx)
            state.slot += 1
            cached.epoch_ctx.rotate(state)
        else:
            state.slot += 1


def state_transition(
    cached: CachedBeaconState,
    signed_block,
    verify_state_root: bool = True,
    verify_proposer: bool = True,
    verify_signatures: bool = True,
) -> CachedBeaconState:
    """Full STF on a CLONE of the input state; returns the post state."""
    post = cached.clone()
    block = signed_block.message
    if post.state.slot < block.slot:
        process_slots(post, block.slot)
    if verify_proposer:
        from .signature_sets import get_block_proposer_signature_set
        from lodestar_tpu.crypto.bls.api import verify_signature_set

        if not verify_signature_set(
            get_block_proposer_signature_set(post.cfg, post.state, post.epoch_ctx, signed_block)
        ):
            raise ValueError("invalid block signature")
    block_phase0.process_block(
        post.cfg, post.state, post.epoch_ctx, block, verify_signatures
    )
    if verify_state_root:
        root = post.hash_tree_root()
        if bytes(block.state_root) != root:
            raise ValueError(
                f"state root mismatch: block {bytes(block.state_root).hex()} != {root.hex()}"
            )
    return post
