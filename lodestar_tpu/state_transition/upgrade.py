"""Fork upgrades (reference:
packages/state-transition/src/slot/upgradeStateTo{Altair,Bellatrix,
Capella,Eip4844}.ts; consensus-specs {altair,bellatrix,capella,eip4844}/
fork.md upgrade functions).
"""
from __future__ import annotations

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
)
from lodestar_tpu.types import ssz
from .epoch_context import EpochContext
from .util.misc import (
    compute_epoch_at_slot,
    get_block_root,
    get_block_root_at_slot,
)
from .util.sync_committee import get_next_sync_committee


def _translate_participation(post, epoch_ctx: EpochContext, pending_attestations) -> None:
    """Spec translate_participation: replay phase0 PendingAttestations into
    previous-epoch participation flags."""
    from .block.altair import get_attestation_participation_flag_indices

    for att in pending_attestations:
        data = att.data
        try:
            flag_indices = get_attestation_participation_flag_indices(
                None, post, data, att.inclusion_delay
            )
        except ValueError:
            continue
        committee = epoch_ctx.get_committee(data.slot, data.index)
        for i, bit in enumerate(att.aggregation_bits):
            if not bit:
                continue
            index = int(committee[i])
            for flag_index in flag_indices:
                post.previous_epoch_participation[index] |= 1 << flag_index


def upgrade_to_altair(cfg, state, epoch_ctx: EpochContext):
    """phase0 BeaconState -> altair BeaconState at the fork boundary."""
    epoch = compute_epoch_at_slot(state.slot)
    n = len(state.validators)
    post = ssz.altair.BeaconState(
        genesis_time=state.genesis_time,
        genesis_validators_root=bytes(state.genesis_validators_root),
        slot=state.slot,
        fork=ssz.phase0.Fork(
            previous_version=bytes(state.fork.current_version),
            current_version=cfg.ALTAIR_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=state.latest_block_header,
        block_roots=list(state.block_roots),
        state_roots=list(state.state_roots),
        historical_roots=list(state.historical_roots),
        eth1_data=state.eth1_data,
        eth1_data_votes=list(state.eth1_data_votes),
        eth1_deposit_index=state.eth1_deposit_index,
        validators=list(state.validators),
        balances=list(state.balances),
        randao_mixes=list(state.randao_mixes),
        slashings=list(state.slashings),
        previous_epoch_participation=[0] * n,
        current_epoch_participation=[0] * n,
        justification_bits=list(state.justification_bits),
        previous_justified_checkpoint=state.previous_justified_checkpoint,
        current_justified_checkpoint=state.current_justified_checkpoint,
        finalized_checkpoint=state.finalized_checkpoint,
        inactivity_scores=[0] * n,
    )
    _translate_participation(post, epoch_ctx, state.previous_epoch_attestations)

    eff = [v.effective_balance for v in post.validators]
    committee, _ = get_next_sync_committee(
        post, epoch_ctx.next_shuffling.active_indices, eff
    )
    post.current_sync_committee = committee
    post.next_sync_committee = committee
    return post


def _copy_shared_fields(post, state) -> None:
    """Copy the altair-and-later field prefix shared by every post-altair
    state shape (all upgrades from bellatrix on are pure field adds)."""
    post.genesis_time = state.genesis_time
    post.genesis_validators_root = bytes(state.genesis_validators_root)
    post.slot = state.slot
    post.latest_block_header = state.latest_block_header
    post.block_roots = list(state.block_roots)
    post.state_roots = list(state.state_roots)
    post.historical_roots = list(state.historical_roots)
    post.eth1_data = state.eth1_data
    post.eth1_data_votes = list(state.eth1_data_votes)
    post.eth1_deposit_index = state.eth1_deposit_index
    post.validators = list(state.validators)
    post.balances = list(state.balances)
    post.randao_mixes = list(state.randao_mixes)
    post.slashings = list(state.slashings)
    post.previous_epoch_participation = list(state.previous_epoch_participation)
    post.current_epoch_participation = list(state.current_epoch_participation)
    post.justification_bits = list(state.justification_bits)
    post.previous_justified_checkpoint = state.previous_justified_checkpoint
    post.current_justified_checkpoint = state.current_justified_checkpoint
    post.finalized_checkpoint = state.finalized_checkpoint
    post.inactivity_scores = list(state.inactivity_scores)
    post.current_sync_committee = state.current_sync_committee
    post.next_sync_committee = state.next_sync_committee


def upgrade_to_bellatrix(cfg, state, epoch_ctx: EpochContext):
    """altair BeaconState -> bellatrix at the fork boundary: adds a default
    (pre-merge) latest_execution_payload_header."""
    epoch = compute_epoch_at_slot(state.slot)
    post = ssz.bellatrix.BeaconState()
    _copy_shared_fields(post, state)
    post.fork = ssz.phase0.Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=cfg.BELLATRIX_FORK_VERSION,
        epoch=epoch,
    )
    post.latest_execution_payload_header = ssz.bellatrix.ExecutionPayloadHeader.default()
    return post


def upgrade_to_capella(cfg, state, epoch_ctx: EpochContext):
    """bellatrix -> capella: header gains withdrawals_root, state gains the
    withdrawal sweep cursors + empty historical_summaries."""
    epoch = compute_epoch_at_slot(state.slot)
    pre_h = state.latest_execution_payload_header
    post = ssz.capella.BeaconState()
    _copy_shared_fields(post, state)
    post.fork = ssz.phase0.Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=cfg.CAPELLA_FORK_VERSION,
        epoch=epoch,
    )
    post.latest_execution_payload_header = ssz.capella.ExecutionPayloadHeader(
        parent_hash=bytes(pre_h.parent_hash),
        fee_recipient=bytes(pre_h.fee_recipient),
        state_root=bytes(pre_h.state_root),
        receipts_root=bytes(pre_h.receipts_root),
        logs_bloom=bytes(pre_h.logs_bloom),
        prev_randao=bytes(pre_h.prev_randao),
        block_number=pre_h.block_number,
        gas_limit=pre_h.gas_limit,
        gas_used=pre_h.gas_used,
        timestamp=pre_h.timestamp,
        extra_data=bytes(pre_h.extra_data),
        base_fee_per_gas=pre_h.base_fee_per_gas,
        block_hash=bytes(pre_h.block_hash),
        transactions_root=bytes(pre_h.transactions_root),
        withdrawals_root=b"\x00" * 32,
    )
    post.next_withdrawal_index = 0
    post.next_withdrawal_validator_index = 0
    post.historical_summaries = []
    return post


def upgrade_to_eip4844(cfg, state, epoch_ctx: EpochContext):
    """capella -> eip4844: header gains excess_data_gas."""
    epoch = compute_epoch_at_slot(state.slot)
    pre_h = state.latest_execution_payload_header
    post = ssz.eip4844.BeaconState()
    _copy_shared_fields(post, state)
    post.fork = ssz.phase0.Fork(
        previous_version=bytes(state.fork.current_version),
        current_version=cfg.EIP4844_FORK_VERSION,
        epoch=epoch,
    )
    post.latest_execution_payload_header = ssz.eip4844.ExecutionPayloadHeader(
        parent_hash=bytes(pre_h.parent_hash),
        fee_recipient=bytes(pre_h.fee_recipient),
        state_root=bytes(pre_h.state_root),
        receipts_root=bytes(pre_h.receipts_root),
        logs_bloom=bytes(pre_h.logs_bloom),
        prev_randao=bytes(pre_h.prev_randao),
        block_number=pre_h.block_number,
        gas_limit=pre_h.gas_limit,
        gas_used=pre_h.gas_used,
        timestamp=pre_h.timestamp,
        extra_data=bytes(pre_h.extra_data),
        base_fee_per_gas=pre_h.base_fee_per_gas,
        excess_data_gas=0,
        block_hash=bytes(pre_h.block_hash),
        transactions_root=bytes(pre_h.transactions_root),
        withdrawals_root=bytes(pre_h.withdrawals_root),
    )
    post.next_withdrawal_index = state.next_withdrawal_index
    post.next_withdrawal_validator_index = state.next_withdrawal_validator_index
    post.historical_summaries = list(state.historical_summaries)
    return post
