"""Fork upgrades (reference:
packages/state-transition/src/slot/upgradeStateToAltair.ts; consensus-specs
altair/fork.md upgrade_to_altair).
"""
from __future__ import annotations

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
)
from lodestar_tpu.types import ssz
from .epoch_context import EpochContext
from .util.misc import (
    compute_epoch_at_slot,
    get_block_root,
    get_block_root_at_slot,
)
from .util.sync_committee import get_next_sync_committee


def _translate_participation(post, epoch_ctx: EpochContext, pending_attestations) -> None:
    """Spec translate_participation: replay phase0 PendingAttestations into
    previous-epoch participation flags."""
    from .block.altair import get_attestation_participation_flag_indices

    for att in pending_attestations:
        data = att.data
        try:
            flag_indices = get_attestation_participation_flag_indices(
                None, post, data, att.inclusion_delay
            )
        except ValueError:
            continue
        committee = epoch_ctx.get_committee(data.slot, data.index)
        for i, bit in enumerate(att.aggregation_bits):
            if not bit:
                continue
            index = int(committee[i])
            for flag_index in flag_indices:
                post.previous_epoch_participation[index] |= 1 << flag_index


def upgrade_to_altair(cfg, state, epoch_ctx: EpochContext):
    """phase0 BeaconState -> altair BeaconState at the fork boundary."""
    epoch = compute_epoch_at_slot(state.slot)
    n = len(state.validators)
    post = ssz.altair.BeaconState(
        genesis_time=state.genesis_time,
        genesis_validators_root=bytes(state.genesis_validators_root),
        slot=state.slot,
        fork=ssz.phase0.Fork(
            previous_version=bytes(state.fork.current_version),
            current_version=cfg.ALTAIR_FORK_VERSION,
            epoch=epoch,
        ),
        latest_block_header=state.latest_block_header,
        block_roots=list(state.block_roots),
        state_roots=list(state.state_roots),
        historical_roots=list(state.historical_roots),
        eth1_data=state.eth1_data,
        eth1_data_votes=list(state.eth1_data_votes),
        eth1_deposit_index=state.eth1_deposit_index,
        validators=list(state.validators),
        balances=list(state.balances),
        randao_mixes=list(state.randao_mixes),
        slashings=list(state.slashings),
        previous_epoch_participation=[0] * n,
        current_epoch_participation=[0] * n,
        justification_bits=list(state.justification_bits),
        previous_justified_checkpoint=state.previous_justified_checkpoint,
        current_justified_checkpoint=state.current_justified_checkpoint,
        finalized_checkpoint=state.finalized_checkpoint,
        inactivity_scores=[0] * n,
    )
    _translate_participation(post, epoch_ctx, state.previous_epoch_attestations)

    eff = [v.effective_balance for v in post.validators]
    committee, _ = get_next_sync_committee(
        post, epoch_ctx.next_shuffling.active_indices, eff
    )
    post.current_sync_committee = committee
    post.next_sync_committee = committee
    return post
