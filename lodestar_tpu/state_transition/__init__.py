from .epoch_context import EpochContext, EpochShuffling, compute_epoch_shuffling  # noqa: F401
from .state_transition import (  # noqa: F401
    CachedBeaconState,
    process_slot,
    process_slots,
    state_transition,
)
