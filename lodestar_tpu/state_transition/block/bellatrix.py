"""Bellatrix (merge) block processing (reference:
packages/state-transition/src/block/processExecutionPayload.ts and the
bellatrix branches of block/index.ts; consensus-specs
bellatrix/beacon-chain.md).

The execution-engine `notify_new_payload` call is decoupled like the
reference: the chain pipeline verifies the payload against the EL in
parallel (chain/blocks/verifyBlock.ts:71-80), and the STF only checks
consensus-visible payload consistency unless an engine is passed in.
"""
from __future__ import annotations

from lodestar_tpu.params import ACTIVE_PRESET as _p, ForkName
from lodestar_tpu.types import fork_of_state, ssz
from ..epoch_context import EpochContext
from ..util.misc import compute_epoch_at_slot, get_randao_mix
from . import altair as ba, phase0 as b0
from .process_deposit import process_deposit


def is_merge_transition_complete(state) -> bool:
    header_t = type(state)._fields_["latest_execution_payload_header"]
    return state.latest_execution_payload_header != header_t.default()


def _body_payload_or_header(body):
    """(value, is_blinded) — blinded bodies carry execution_payload_header
    (spec process_execution_payload(header) for blinded blocks)."""
    if hasattr(body, "execution_payload"):
        return body.execution_payload, False
    return body.execution_payload_header, True


def is_merge_transition_block(state, body) -> bool:
    field = (
        "execution_payload"
        if hasattr(body, "execution_payload")
        else "execution_payload_header"
    )
    payload_t = type(body)._fields_[field]
    return (
        not is_merge_transition_complete(state)
        and getattr(body, field) != payload_t.default()
    )


def is_execution_enabled(state, body) -> bool:
    return is_merge_transition_block(state, body) or is_merge_transition_complete(state)


def compute_timestamp_at_slot(cfg, state, slot: int) -> int:
    slots_since_genesis = slot - 0
    return state.genesis_time + slots_since_genesis * cfg.SECONDS_PER_SLOT


def process_execution_payload(cfg, state, body, execution_engine=None) -> None:
    """Spec process_execution_payload: consistency checks + header store.

    The parent_hash check is gated on merge completion only for bellatrix;
    capella+ assert it unconditionally (capella/beacon-chain.md)."""
    from lodestar_tpu.params import FORK_SEQ

    payload, blinded = _body_payload_or_header(body)
    fork = fork_of_state(state)
    post_capella = FORK_SEQ[fork] >= FORK_SEQ[ForkName.capella]
    if post_capella or is_merge_transition_complete(state):
        if bytes(payload.parent_hash) != bytes(
            state.latest_execution_payload_header.block_hash
        ):
            raise ValueError("execution payload parent_hash mismatch")
    epoch = compute_epoch_at_slot(state.slot)
    if bytes(payload.prev_randao) != get_randao_mix(state, epoch):
        raise ValueError("execution payload prev_randao mismatch")
    if payload.timestamp != compute_timestamp_at_slot(cfg, state, state.slot):
        raise ValueError("execution payload timestamp mismatch")
    if blinded:
        # blinded STF (spec process_execution_payload over the header):
        # the committed header IS the state's new latest header; the full
        # payload is revealed out-of-band by the builder on submission
        state.latest_execution_payload_header = payload.copy()
        return
    if execution_engine is not None:
        if not execution_engine.notify_new_payload_sync(payload):
            raise ValueError("execution engine rejected payload")
    # fork-matched header conversion (bellatrix/capella/eip4844 modules each
    # export payload_to_header for their payload shape)
    mod = getattr(ssz, fork.value)
    state.latest_execution_payload_header = mod.payload_to_header(payload)


def process_block(
    cfg, state, epoch_ctx: EpochContext, block, verify_signatures: bool = True,
    execution_engine=None,
) -> None:
    b0.process_block_header(cfg, state, epoch_ctx, block)
    if is_execution_enabled(state, block.body):
        process_execution_payload(cfg, state, block.body, execution_engine)
    b0.process_randao(cfg, state, epoch_ctx, block.body, verify_signatures)
    b0.process_eth1_data(cfg, state, block.body)
    process_operations(cfg, state, epoch_ctx, block.body, verify_signatures)
    ba.process_sync_aggregate(cfg, state, epoch_ctx, block, verify_signatures)


def process_operations(
    cfg, state, epoch_ctx: EpochContext, body, verify_signatures: bool = True
) -> None:
    expected_deposits = min(
        _p.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    if len(body.deposits) != expected_deposits:
        raise ValueError(
            f"expected {expected_deposits} deposits, block has {len(body.deposits)}"
        )
    for ps in body.proposer_slashings:
        b0.process_proposer_slashing(cfg, state, epoch_ctx, ps, verify_signatures)
    for asl in body.attester_slashings:
        b0.process_attester_slashing(cfg, state, epoch_ctx, asl, verify_signatures)
    for att in body.attestations:
        ba.process_attestation(cfg, state, epoch_ctx, att, verify_signatures)
    for dep in body.deposits:
        process_deposit(ForkName.bellatrix, cfg, state, dep, epoch_ctx.pubkey2index)
    for ex in body.voluntary_exits:
        b0.process_voluntary_exit(cfg, state, epoch_ctx, ex, verify_signatures)
