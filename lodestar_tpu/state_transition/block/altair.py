"""Altair block processing (reference:
packages/state-transition/src/block/{processAttestationsAltair,
processSyncCommittee}.ts; consensus-specs altair/beacon-chain.md).

Attestations set per-validator participation FLAG BITS (replacing phase0's
PendingAttestation lists) and pay the proposer immediately; the sync
aggregate is verified against the previous slot's block root and pays
participants + proposer.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import math

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DOMAIN_SYNC_COMMITTEE,
    FORK_SEQ,
    ForkName,
    PARTICIPATION_FLAG_WEIGHTS,
    PROPOSER_WEIGHT,
    SYNC_REWARD_WEIGHT,
    TIMELY_HEAD_FLAG_INDEX,
    TIMELY_SOURCE_FLAG_INDEX,
    TIMELY_TARGET_FLAG_INDEX,
    WEIGHT_DENOMINATOR,
)
from lodestar_tpu.types import ssz
from ..epoch_context import EpochContext
from ..util.domain import compute_signing_root
from ..util.misc import (
    compute_epoch_at_slot,
    get_block_root,
    get_block_root_at_slot,
)
from . import phase0 as b0
from .process_deposit import process_deposit


def get_base_reward_per_increment(total_active_balance: int) -> int:
    return (
        _p.EFFECTIVE_BALANCE_INCREMENT
        * _p.BASE_REWARD_FACTOR
        // math.isqrt(total_active_balance)
    )


def get_base_reward(state, epoch_ctx: EpochContext, index: int,
                    base_reward_per_increment: Optional[int] = None) -> int:
    if base_reward_per_increment is None:
        base_reward_per_increment = get_base_reward_per_increment(
            epoch_ctx.total_active_balance_increments() * _p.EFFECTIVE_BALANCE_INCREMENT
        )
    increments = state.validators[index].effective_balance // _p.EFFECTIVE_BALANCE_INCREMENT
    return increments * base_reward_per_increment


def get_attestation_participation_flag_indices(
    cfg, state, data, inclusion_delay: int
) -> List[int]:
    """Spec get_attestation_participation_flag_indices."""
    epoch = compute_epoch_at_slot(state.slot)
    if data.target.epoch == epoch:
        justified = state.current_justified_checkpoint
    else:
        justified = state.previous_justified_checkpoint
    if data.source != justified:
        raise ValueError("attestation source != justified checkpoint")
    is_matching_source = True
    try:
        is_matching_target = bytes(data.target.root) == get_block_root(
            state, data.target.epoch
        )
    except ValueError:
        is_matching_target = False
    is_matching_head = False
    if is_matching_target:
        try:
            is_matching_head = bytes(data.beacon_block_root) == get_block_root_at_slot(
                state, data.slot
            )
        except ValueError:
            is_matching_head = False

    flags: List[int] = []
    if is_matching_source and inclusion_delay <= int(
        math.isqrt(_p.SLOTS_PER_EPOCH)
    ):
        flags.append(TIMELY_SOURCE_FLAG_INDEX)
    if is_matching_target and inclusion_delay <= _p.SLOTS_PER_EPOCH:
        flags.append(TIMELY_TARGET_FLAG_INDEX)
    if is_matching_head and inclusion_delay == _p.MIN_ATTESTATION_INCLUSION_DELAY:
        flags.append(TIMELY_HEAD_FLAG_INDEX)
    return flags


def process_attestation(
    cfg, state, epoch_ctx: EpochContext, attestation, verify_signature: bool = True
) -> None:
    """Altair processAttestation: same structural checks as phase0, then
    flag updates + proposer reward instead of PendingAttestation append."""
    data = attestation.data
    epoch = compute_epoch_at_slot(state.slot)
    previous_epoch = max(0, epoch - 1)
    if data.target.epoch not in (previous_epoch, epoch):
        raise ValueError("attestation target epoch out of range")
    if data.target.epoch != compute_epoch_at_slot(data.slot):
        raise ValueError("attestation target/slot mismatch")
    if not (
        data.slot + _p.MIN_ATTESTATION_INCLUSION_DELAY
        <= state.slot
        <= data.slot + _p.SLOTS_PER_EPOCH
    ):
        raise ValueError("attestation inclusion window")
    if data.index >= epoch_ctx.get_committee_count_per_slot(data.target.epoch):
        raise ValueError("attestation committee index out of range")

    inclusion_delay = state.slot - data.slot
    flag_indices = get_attestation_participation_flag_indices(
        cfg, state, data, inclusion_delay
    )

    indexed = b0.get_indexed_attestation(epoch_ctx, attestation)
    if not b0.is_valid_indexed_attestation(cfg, state, indexed, verify_signature):
        raise ValueError("invalid attestation (indices/signature)")

    participation = (
        state.current_epoch_participation
        if data.target.epoch == epoch
        else state.previous_epoch_participation
    )
    base_reward_per_increment = get_base_reward_per_increment(
        epoch_ctx.total_active_balance_increments() * _p.EFFECTIVE_BALANCE_INCREMENT
    )
    proposer_reward_numerator = 0
    for index in indexed.attesting_indices:
        for flag_index, weight in enumerate(PARTICIPATION_FLAG_WEIGHTS):
            if flag_index in flag_indices and not (
                participation[index] & (1 << flag_index)
            ):
                participation[index] |= 1 << flag_index
                proposer_reward_numerator += (
                    get_base_reward(state, epoch_ctx, index, base_reward_per_increment)
                    * weight
                )
    proposer_reward_denominator = (
        (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT) * WEIGHT_DENOMINATOR // PROPOSER_WEIGHT
    )
    proposer_reward = proposer_reward_numerator // proposer_reward_denominator
    proposer = epoch_ctx.get_beacon_proposer(state.slot)
    state.balances[proposer] += proposer_reward


# ---------------------------------------------------------------------------
# sync aggregate
# ---------------------------------------------------------------------------


def get_sync_committee_indices(state, epoch_ctx: EpochContext) -> List[int]:
    """Validator indices of state.current_sync_committee (cached on the
    epoch context; the reference keeps this in EpochContext
    currentSyncCommitteeIndexed)."""
    cache = getattr(epoch_ctx, "_sync_committee_indices", None)
    key = bytes(state.current_sync_committee.aggregate_pubkey)
    if cache is not None and cache[0] == key:
        return cache[1]
    indices = [
        epoch_ctx.pubkey2index[bytes(pk)]
        for pk in state.current_sync_committee.pubkeys
    ]
    epoch_ctx._sync_committee_indices = (key, indices)
    return indices


def get_sync_aggregate_signature_set(cfg, state, epoch_ctx, block):
    """The sync aggregate's BLS set: participants sign the PREVIOUS slot's
    block root (signatureSets/syncCommittee role)."""
    agg = block.body.sync_aggregate
    previous_slot = max(1, block.slot) - 1
    root = get_block_root_at_slot(state, previous_slot)
    domain = b0.get_domain(
        cfg, state, DOMAIN_SYNC_COMMITTEE, compute_epoch_at_slot(previous_slot)
    )
    signing_root = compute_signing_root(ssz.phase0.Root, root, domain)
    pks = [
        bls.PublicKey.from_bytes(bytes(pk))
        for pk, bit in zip(state.current_sync_committee.pubkeys, agg.sync_committee_bits)
        if bit
    ]
    if not pks:
        return None
    return bls.SignatureSet(
        bls.aggregate_public_keys(pks),
        signing_root,
        bls.Signature.from_bytes(bytes(agg.sync_committee_signature)),
    )


def process_sync_aggregate(
    cfg, state, epoch_ctx: EpochContext, block, verify_signature: bool = True
) -> None:
    agg = block.body.sync_aggregate
    if verify_signature:
        sig_set = get_sync_aggregate_signature_set(cfg, state, epoch_ctx, block)
        if sig_set is not None and not bls.verify_signature_set(sig_set):
            raise ValueError("invalid sync aggregate signature")
        if sig_set is None and bls.Signature.from_bytes(
            bytes(agg.sync_committee_signature)
        ).point is not None:
            raise ValueError("empty sync aggregate must carry infinity signature")

    # participant + proposer rewards (spec process_sync_aggregate)
    total_active_increments = epoch_ctx.total_active_balance_increments()
    total_base_rewards = get_base_reward_per_increment(
        total_active_increments * _p.EFFECTIVE_BALANCE_INCREMENT
    ) * total_active_increments
    max_participant_rewards = (
        total_base_rewards * SYNC_REWARD_WEIGHT // WEIGHT_DENOMINATOR // _p.SLOTS_PER_EPOCH
    )
    participant_reward = max_participant_rewards // _p.SYNC_COMMITTEE_SIZE
    proposer_reward = (
        participant_reward * PROPOSER_WEIGHT // (WEIGHT_DENOMINATOR - PROPOSER_WEIGHT)
    )
    proposer = epoch_ctx.get_beacon_proposer(state.slot)
    committee_indices = get_sync_committee_indices(state, epoch_ctx)
    for i, bit in enumerate(agg.sync_committee_bits):
        participant = committee_indices[i]
        if bit:
            state.balances[participant] += participant_reward
            state.balances[proposer] += proposer_reward
        else:
            state.balances[participant] = max(
                0, state.balances[participant] - participant_reward
            )


# ---------------------------------------------------------------------------
# the block body
# ---------------------------------------------------------------------------


def process_operations(
    cfg, state, epoch_ctx: EpochContext, body, verify_signatures: bool = True
) -> None:
    expected_deposits = min(
        _p.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    if len(body.deposits) != expected_deposits:
        raise ValueError(
            f"expected {expected_deposits} deposits, block has {len(body.deposits)}"
        )
    for ps in body.proposer_slashings:
        b0.process_proposer_slashing(cfg, state, epoch_ctx, ps, verify_signatures)
    for asl in body.attester_slashings:
        b0.process_attester_slashing(cfg, state, epoch_ctx, asl, verify_signatures)
    for att in body.attestations:
        process_attestation(cfg, state, epoch_ctx, att, verify_signatures)
    for dep in body.deposits:
        process_deposit(ForkName.altair, cfg, state, dep, epoch_ctx.pubkey2index)
    for ex in body.voluntary_exits:
        b0.process_voluntary_exit(cfg, state, epoch_ctx, ex, verify_signatures)


def process_block(
    cfg, state, epoch_ctx: EpochContext, block, verify_signatures: bool = True
) -> None:
    b0.process_block_header(cfg, state, epoch_ctx, block)
    b0.process_randao(cfg, state, epoch_ctx, block.body, verify_signatures)
    b0.process_eth1_data(cfg, state, block.body)
    process_operations(cfg, state, epoch_ctx, block.body, verify_signatures)
    process_sync_aggregate(cfg, state, epoch_ctx, block, verify_signatures)
