"""Capella block processing (reference:
packages/state-transition/src/block/{processWithdrawals,
processBlsToExecutionChange}.ts; consensus-specs capella/beacon-chain.md).
"""
from __future__ import annotations

from typing import List, Tuple

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    BLS_WITHDRAWAL_PREFIX,
    DOMAIN_BLS_TO_EXECUTION_CHANGE,
    ETH1_ADDRESS_WITHDRAWAL_PREFIX,
    FAR_FUTURE_EPOCH,
    ForkName,
)
from lodestar_tpu.types import ssz
from ..epoch_context import EpochContext
from ..util.domain import compute_domain, compute_signing_root
from ..util.misc import compute_epoch_at_slot, decrease_balance, sha256
from . import altair as ba, bellatrix as bm, phase0 as b0
from .process_deposit import process_deposit


def has_eth1_withdrawal_credential(validator) -> bool:
    return bytes(validator.withdrawal_credentials)[:1] == bytes(
        [ETH1_ADDRESS_WITHDRAWAL_PREFIX]
    )


def is_fully_withdrawable_validator(validator, balance: int, epoch: int) -> bool:
    return (
        has_eth1_withdrawal_credential(validator)
        and validator.withdrawable_epoch <= epoch
        and balance > 0
    )


def is_partially_withdrawable_validator(validator, balance: int) -> bool:
    return (
        has_eth1_withdrawal_credential(validator)
        and validator.effective_balance == _p.MAX_EFFECTIVE_BALANCE
        and balance > _p.MAX_EFFECTIVE_BALANCE
    )


def get_expected_withdrawals(state) -> List:
    """Spec get_expected_withdrawals: the bounded validator sweep from
    next_withdrawal_validator_index."""
    epoch = compute_epoch_at_slot(state.slot)
    withdrawal_index = state.next_withdrawal_index
    validator_index = state.next_withdrawal_validator_index
    withdrawals = []
    n = len(state.validators)
    for _ in range(min(n, _p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP)):
        v = state.validators[validator_index]
        balance = state.balances[validator_index]
        if is_fully_withdrawable_validator(v, balance, epoch):
            withdrawals.append(
                ssz.capella.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=bytes(v.withdrawal_credentials)[12:],
                    amount=balance,
                )
            )
            withdrawal_index += 1
        elif is_partially_withdrawable_validator(v, balance):
            withdrawals.append(
                ssz.capella.Withdrawal(
                    index=withdrawal_index,
                    validator_index=validator_index,
                    address=bytes(v.withdrawal_credentials)[12:],
                    amount=balance - _p.MAX_EFFECTIVE_BALANCE,
                )
            )
            withdrawal_index += 1
        if len(withdrawals) == _p.MAX_WITHDRAWALS_PER_PAYLOAD:
            break
        validator_index = (validator_index + 1) % n
    return withdrawals


def process_withdrawals(cfg, state, payload) -> None:
    """Full payloads compare withdrawal-by-withdrawal; blinded headers
    compare the committed withdrawals_root (spec blinded process_withdrawals)."""
    expected = get_expected_withdrawals(state)
    if hasattr(payload, "withdrawals"):
        got = list(payload.withdrawals)
        if len(got) != len(expected):
            raise ValueError(
                f"withdrawals count mismatch: payload {len(got)} != expected {len(expected)}"
            )
        for w, e in zip(got, expected):
            if w != e:
                raise ValueError("withdrawal mismatch")
    else:
        wl_t = ssz.capella.ExecutionPayload._fields_["withdrawals"]
        if bytes(payload.withdrawals_root) != wl_t.hash_tree_root(expected):
            raise ValueError("blinded withdrawals_root mismatch")
    for w in expected:
        decrease_balance(state, w.validator_index, w.amount)
    if expected:
        state.next_withdrawal_index = expected[-1].index + 1
    n = len(state.validators)
    if len(expected) == _p.MAX_WITHDRAWALS_PER_PAYLOAD:
        # the sweep stopped at the last withdrawal — resume after it
        state.next_withdrawal_validator_index = (
            expected[-1].validator_index + 1
        ) % n
    else:
        # full sweep bound hit — resume after the sweep window (spec uses
        # the RAW sweep constant even when it exceeds the validator count)
        state.next_withdrawal_validator_index = (
            state.next_withdrawal_validator_index
            + _p.MAX_VALIDATORS_PER_WITHDRAWALS_SWEEP
        ) % n


def get_bls_to_execution_change_signature_set(cfg, state, signed_change):
    """BLSToExecutionChange signs with GENESIS fork version regardless of
    the current fork (spec process_bls_to_execution_change)."""
    change = signed_change.message
    domain = compute_domain(
        DOMAIN_BLS_TO_EXECUTION_CHANGE,
        cfg.GENESIS_FORK_VERSION,
        bytes(state.genesis_validators_root),
    )
    signing_root = compute_signing_root(
        ssz.capella.BLSToExecutionChange, change, domain
    )
    return bls.SignatureSet(
        bls.PublicKey.from_bytes(bytes(change.from_bls_pubkey)),
        signing_root,
        bls.Signature.from_bytes(bytes(signed_change.signature)),
    )


def check_bls_to_execution_change_preconditions(state, change) -> None:
    """Stateless validity checks shared by the STF and gossip validation
    (raises ValueError on failure)."""
    if change.validator_index >= len(state.validators):
        raise ValueError("bls_to_execution_change: unknown validator")
    v = state.validators[change.validator_index]
    creds = bytes(v.withdrawal_credentials)
    if creds[:1] != bytes([BLS_WITHDRAWAL_PREFIX]):
        raise ValueError("bls_to_execution_change: not BLS credentials")
    if creds[1:] != sha256(bytes(change.from_bls_pubkey))[1:]:
        raise ValueError("bls_to_execution_change: pubkey/credentials mismatch")


def process_bls_to_execution_change(
    cfg, state, signed_change, verify_signature: bool = True
) -> None:
    change = signed_change.message
    check_bls_to_execution_change_preconditions(state, change)
    v = state.validators[change.validator_index]
    if verify_signature and not bls.verify_signature_set(
        get_bls_to_execution_change_signature_set(cfg, state, signed_change)
    ):
        raise ValueError("bls_to_execution_change: invalid signature")
    state.validators[change.validator_index] = v.replace(
        withdrawal_credentials=(
            bytes([ETH1_ADDRESS_WITHDRAWAL_PREFIX])
            + b"\x00" * 11
            + bytes(change.to_execution_address)
        )
    )


def process_operations(
    cfg, state, epoch_ctx: EpochContext, body, verify_signatures: bool = True,
    deposit_fork: ForkName = ForkName.capella,
) -> None:
    expected_deposits = min(
        _p.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    if len(body.deposits) != expected_deposits:
        raise ValueError(
            f"expected {expected_deposits} deposits, block has {len(body.deposits)}"
        )
    for ps in body.proposer_slashings:
        b0.process_proposer_slashing(cfg, state, epoch_ctx, ps, verify_signatures)
    for asl in body.attester_slashings:
        b0.process_attester_slashing(cfg, state, epoch_ctx, asl, verify_signatures)
    for att in body.attestations:
        ba.process_attestation(cfg, state, epoch_ctx, att, verify_signatures)
    for dep in body.deposits:
        process_deposit(deposit_fork, cfg, state, dep, epoch_ctx.pubkey2index)
    for ex in body.voluntary_exits:
        b0.process_voluntary_exit(cfg, state, epoch_ctx, ex, verify_signatures)
    for chg in body.bls_to_execution_changes:
        process_bls_to_execution_change(cfg, state, chg, verify_signatures)


def process_block(
    cfg, state, epoch_ctx: EpochContext, block, verify_signatures: bool = True,
    execution_engine=None,
) -> None:
    b0.process_block_header(cfg, state, epoch_ctx, block)
    if bm.is_execution_enabled(state, block.body):
        process_withdrawals(cfg, state, bm._body_payload_or_header(block.body)[0])
        bm.process_execution_payload(cfg, state, block.body, execution_engine)
    b0.process_randao(cfg, state, epoch_ctx, block.body, verify_signatures)
    b0.process_eth1_data(cfg, state, block.body)
    process_operations(cfg, state, epoch_ctx, block.body, verify_signatures)
    ba.process_sync_aggregate(cfg, state, epoch_ctx, block, verify_signatures)
