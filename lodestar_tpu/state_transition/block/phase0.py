"""Phase0 block processing (reference:
packages/state-transition/src/block/*.ts, consensus-specs phase0).

All functions mutate `state` in place and raise ValueError on invalid
blocks.  Signature verification is SEPARABLE: pass verify_signatures=False
and feed the extracted signature sets to the BLS verifier instead (the
reference's verifyBlocksSignatures / getBlockSignatureSets split,
chain/blocks/verifyBlock.ts:71-80) — the TPU-first import pipeline runs
the state transition and the device batch verification in parallel.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_VOLUNTARY_EXIT,
    FAR_FUTURE_EPOCH,
    ForkName,
)
from lodestar_tpu.types import ssz
from ..epoch_context import EpochContext
from ..util.domain import compute_signing_root
from ..util.misc import (
    compute_activation_exit_epoch,
    compute_epoch_at_slot,
    decrease_balance,
    get_randao_mix,
    get_validator_churn_limit,
    increase_balance,
    int_to_bytes,
    is_active_validator,
    sha256,
)
from .process_deposit import process_deposit


def get_domain(cfg, state, domain_type: bytes, epoch: Optional[int] = None) -> bytes:
    """spec get_domain using the state's fork + genesis_validators_root."""
    from ..util.domain import compute_domain

    epoch = compute_epoch_at_slot(state.slot) if epoch is None else epoch
    fork_version = (
        state.fork.previous_version
        if epoch < state.fork.epoch
        else state.fork.current_version
    )
    return compute_domain(domain_type, fork_version, state.genesis_validators_root)


# ---------------------------------------------------------------------------
# header / randao / eth1
# ---------------------------------------------------------------------------


def process_block_header(cfg, state, epoch_ctx: EpochContext, block) -> None:
    if block.slot != state.slot:
        raise ValueError(f"block slot {block.slot} != state slot {state.slot}")
    if block.slot <= state.latest_block_header.slot:
        raise ValueError("block older than latest header")
    if block.proposer_index != epoch_ctx.get_beacon_proposer(block.slot):
        raise ValueError("wrong proposer index")
    parent_root = ssz.phase0.BeaconBlockHeader.hash_tree_root(
        state.latest_block_header
    )
    if bytes(block.parent_root) != parent_root:
        raise ValueError("parent root mismatch")
    body_t = type(block)._fields_["body"]
    state.latest_block_header = ssz.phase0.BeaconBlockHeader(
        slot=block.slot,
        proposer_index=block.proposer_index,
        parent_root=block.parent_root,
        state_root=b"\x00" * 32,  # filled at next process_slot
        body_root=body_t.hash_tree_root(block.body),
    )
    proposer = state.validators[block.proposer_index]
    if proposer.slashed:
        raise ValueError("proposer slashed")


def process_randao(
    cfg, state, epoch_ctx: EpochContext, body, verify_signature: bool = True
) -> None:
    epoch = compute_epoch_at_slot(state.slot)
    if verify_signature:
        proposer = state.validators[epoch_ctx.get_beacon_proposer(state.slot)]
        domain = get_domain(cfg, state, DOMAIN_RANDAO)
        root = compute_signing_root(
            ssz.phase0.Epoch, epoch, domain
        )
        if not bls.verify(
            bls.PublicKey.from_bytes(bytes(proposer.pubkey)),
            root,
            bls.Signature.from_bytes(bytes(body.randao_reveal)),
        ):
            raise ValueError("invalid randao reveal")
    mix = bytes(
        a ^ b
        for a, b in zip(
            get_randao_mix(state, epoch), sha256(bytes(body.randao_reveal))
        )
    )
    state.randao_mixes[epoch % _p.EPOCHS_PER_HISTORICAL_VECTOR] = mix


def process_eth1_data(cfg, state, body) -> None:
    state.eth1_data_votes.append(body.eth1_data)
    votes = sum(
        1 for v in state.eth1_data_votes if v == body.eth1_data
    )
    period_slots = _p.EPOCHS_PER_ETH1_VOTING_PERIOD * _p.SLOTS_PER_EPOCH
    if votes * 2 > period_slots:
        state.eth1_data = body.eth1_data


# ---------------------------------------------------------------------------
# slashings / exits
# ---------------------------------------------------------------------------


def initiate_validator_exit(cfg, state, epoch_ctx, index: int) -> None:
    """Queue a validator exit.  The exit-queue scan is O(V) ONCE per epoch
    context and updated incrementally thereafter (the reference caches
    exitQueueEpoch/exitQueueChurn/churnLimit on EpochContext the same way,
    epochContext.ts initiateValidatorExit)."""
    v = state.validators[index]
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        return
    epoch = compute_epoch_at_slot(state.slot)
    if epoch_ctx.exit_queue_epoch is None:
        exit_epochs = [
            u.exit_epoch for u in state.validators if u.exit_epoch != FAR_FUTURE_EPOCH
        ]
        eq = max(exit_epochs + [compute_activation_exit_epoch(epoch)])
        epoch_ctx.exit_queue_epoch = eq
        epoch_ctx.exit_queue_churn = sum(
            1 for u in state.validators if u.exit_epoch == eq
        )
        epoch_ctx.churn_limit = get_validator_churn_limit(
            cfg, sum(1 for u in state.validators if is_active_validator(u, epoch))
        )
    else:
        # keep the floor in sync with the advancing epoch
        floor = compute_activation_exit_epoch(epoch)
        if floor > epoch_ctx.exit_queue_epoch:
            epoch_ctx.exit_queue_epoch = floor
            epoch_ctx.exit_queue_churn = 0
    if epoch_ctx.exit_queue_churn >= epoch_ctx.churn_limit:
        epoch_ctx.exit_queue_epoch += 1
        epoch_ctx.exit_queue_churn = 0
    epoch_ctx.exit_queue_churn += 1
    state.validators[index] = v.replace(
        exit_epoch=epoch_ctx.exit_queue_epoch,
        withdrawable_epoch=(
            epoch_ctx.exit_queue_epoch + cfg.MIN_VALIDATOR_WITHDRAWABILITY_DELAY
        ),
    )


def slash_validator(
    cfg, state, epoch_ctx: EpochContext, index: int, whistleblower: Optional[int] = None
) -> None:
    epoch = compute_epoch_at_slot(state.slot)
    initiate_validator_exit(cfg, state, epoch_ctx, index)
    v = state.validators[index]
    v = state.validators[index] = v.replace(
        slashed=True,
        withdrawable_epoch=max(
            v.withdrawable_epoch, epoch + _p.EPOCHS_PER_SLASHINGS_VECTOR
        ),
    )
    state.slashings[epoch % _p.EPOCHS_PER_SLASHINGS_VECTOR] += v.effective_balance
    # fork-dependent quotients (altair/bellatrix "Modified slash_validator")
    from lodestar_tpu.params import PROPOSER_WEIGHT, WEIGHT_DENOMINATOR, ForkName
    from lodestar_tpu.types import fork_of_state
    from ..fork_params import min_slashing_penalty_quotient

    fork = fork_of_state(state)
    decrease_balance(
        state, index, v.effective_balance // min_slashing_penalty_quotient(fork)
    )
    proposer_index = epoch_ctx.get_beacon_proposer(state.slot)
    whistleblower_index = whistleblower if whistleblower is not None else proposer_index
    whistleblower_reward = v.effective_balance // _p.WHISTLEBLOWER_REWARD_QUOTIENT
    if fork is ForkName.phase0:
        proposer_reward = whistleblower_reward // _p.PROPOSER_REWARD_QUOTIENT
    else:
        proposer_reward = whistleblower_reward * PROPOSER_WEIGHT // WEIGHT_DENOMINATOR
    increase_balance(state, proposer_index, proposer_reward)
    increase_balance(state, whistleblower_index, whistleblower_reward - proposer_reward)


def is_slashable_validator(v, epoch: int) -> bool:
    return (not v.slashed) and v.activation_epoch <= epoch < v.withdrawable_epoch


def is_slashable_attestation_data(d1, d2) -> bool:
    # double vote or surround vote
    return (
        d1 != d2 and d1.target.epoch == d2.target.epoch
    ) or (d1.source.epoch < d2.source.epoch and d2.target.epoch < d1.target.epoch)


def is_valid_indexed_attestation(
    cfg, state, indexed, verify_signature: bool = True
) -> bool:
    indices = list(indexed.attesting_indices)
    if not indices or indices != sorted(set(indices)):
        return False
    if verify_signature:
        pubkeys = [
            bls.PublicKey.from_bytes(bytes(state.validators[i].pubkey))
            for i in indices
        ]
        domain = get_domain(
            cfg, state, DOMAIN_BEACON_ATTESTER, indexed.data.target.epoch
        )
        root = compute_signing_root(
            ssz.phase0.AttestationData, indexed.data, domain
        )
        return bls.fast_aggregate_verify(
            pubkeys, root, bls.Signature.from_bytes(bytes(indexed.signature))
        )
    return True


def process_proposer_slashing(
    cfg, state, epoch_ctx: EpochContext, ps, verify_signatures: bool = True
) -> None:
    h1, h2 = ps.signed_header_1.message, ps.signed_header_2.message
    if h1.slot != h2.slot:
        raise ValueError("proposer slashing: different slots")
    if h1.proposer_index != h2.proposer_index:
        raise ValueError("proposer slashing: different proposers")
    if h1 == h2:
        raise ValueError("proposer slashing: identical headers")
    proposer = state.validators[h1.proposer_index]
    if not is_slashable_validator(proposer, compute_epoch_at_slot(state.slot)):
        raise ValueError("proposer not slashable")
    if verify_signatures:
        for signed in (ps.signed_header_1, ps.signed_header_2):
            domain = get_domain(
                cfg,
                state,
                DOMAIN_BEACON_PROPOSER,
                compute_epoch_at_slot(signed.message.slot),
            )
            root = compute_signing_root(
                ssz.phase0.BeaconBlockHeader, signed.message, domain
            )
            if not bls.verify(
                bls.PublicKey.from_bytes(bytes(proposer.pubkey)),
                root,
                bls.Signature.from_bytes(bytes(signed.signature)),
            ):
                raise ValueError("proposer slashing: bad signature")
    slash_validator(cfg, state, epoch_ctx, h1.proposer_index)


def process_attester_slashing(
    cfg, state, epoch_ctx: EpochContext, att_slashing, verify_signatures: bool = True
) -> None:
    a1, a2 = att_slashing.attestation_1, att_slashing.attestation_2
    if not is_slashable_attestation_data(a1.data, a2.data):
        raise ValueError("attestations not slashable")
    for a in (a1, a2):
        if not is_valid_indexed_attestation(cfg, state, a, verify_signatures):
            raise ValueError("invalid indexed attestation")
    epoch = compute_epoch_at_slot(state.slot)
    slashed_any = False
    common = set(a1.attesting_indices) & set(a2.attesting_indices)
    for index in sorted(common):
        if is_slashable_validator(state.validators[index], epoch):
            slash_validator(cfg, state, epoch_ctx, index)
            slashed_any = True
    if not slashed_any:
        raise ValueError("no slashable indices")


def process_voluntary_exit(
    cfg, state, epoch_ctx, signed_exit, verify_signature: bool = True
) -> None:
    exit_ = signed_exit.message
    v = state.validators[exit_.validator_index]
    epoch = compute_epoch_at_slot(state.slot)
    if not is_active_validator(v, epoch):
        raise ValueError("exit: validator not active")
    if v.exit_epoch != FAR_FUTURE_EPOCH:
        raise ValueError("exit: already exiting")
    if epoch < exit_.epoch:
        raise ValueError("exit: not yet valid")
    if epoch < v.activation_epoch + cfg.SHARD_COMMITTEE_PERIOD:
        raise ValueError("exit: too young")
    if verify_signature:
        domain = get_domain(cfg, state, DOMAIN_VOLUNTARY_EXIT, exit_.epoch)
        root = compute_signing_root(ssz.phase0.VoluntaryExit, exit_, domain)
        if not bls.verify(
            bls.PublicKey.from_bytes(bytes(v.pubkey)),
            root,
            bls.Signature.from_bytes(bytes(signed_exit.signature)),
        ):
            raise ValueError("exit: bad signature")
    initiate_validator_exit(cfg, state, epoch_ctx, exit_.validator_index)


# ---------------------------------------------------------------------------
# attestations
# ---------------------------------------------------------------------------


def get_attesting_indices(epoch_ctx: EpochContext, data, aggregation_bits) -> List[int]:
    committee = epoch_ctx.get_committee(data.slot, data.index)
    if len(aggregation_bits) != len(committee):
        raise ValueError("aggregation bits length mismatch")
    return [int(committee[i]) for i, bit in enumerate(aggregation_bits) if bit]


def get_indexed_attestation(epoch_ctx: EpochContext, attestation):
    indices = get_attesting_indices(
        epoch_ctx, attestation.data, attestation.aggregation_bits
    )
    return ssz.phase0.IndexedAttestation(
        attesting_indices=sorted(indices),
        data=attestation.data,
        signature=attestation.signature,
    )


def process_attestation(
    cfg, state, epoch_ctx: EpochContext, attestation, verify_signature: bool = True
) -> None:
    data = attestation.data
    epoch = compute_epoch_at_slot(state.slot)
    previous_epoch = max(0, epoch - 1)
    if data.target.epoch not in (previous_epoch, epoch):
        raise ValueError("attestation target epoch out of range")
    if data.target.epoch != compute_epoch_at_slot(data.slot):
        raise ValueError("attestation target/slot mismatch")
    if not (
        data.slot + _p.MIN_ATTESTATION_INCLUSION_DELAY
        <= state.slot
        <= data.slot + _p.SLOTS_PER_EPOCH
    ):
        raise ValueError("attestation inclusion window")
    if data.index >= epoch_ctx.get_committee_count_per_slot(data.target.epoch):
        raise ValueError("attestation committee index out of range")

    pending = ssz.phase0.PendingAttestation(
        aggregation_bits=list(attestation.aggregation_bits),
        data=data,
        inclusion_delay=state.slot - data.slot,
        proposer_index=epoch_ctx.get_beacon_proposer(state.slot),
    )
    if data.target.epoch == epoch:
        if data.source != state.current_justified_checkpoint:
            raise ValueError("attestation source != current justified")
        state.current_epoch_attestations.append(pending)
    else:
        if data.source != state.previous_justified_checkpoint:
            raise ValueError("attestation source != previous justified")
        state.previous_epoch_attestations.append(pending)

    indexed = get_indexed_attestation(epoch_ctx, attestation)
    if not is_valid_indexed_attestation(cfg, state, indexed, verify_signature):
        raise ValueError("invalid attestation (indices/signature)")


# ---------------------------------------------------------------------------
# the block body
# ---------------------------------------------------------------------------


def process_operations(
    cfg, state, epoch_ctx: EpochContext, body, verify_signatures: bool = True
) -> None:
    expected_deposits = min(
        _p.MAX_DEPOSITS,
        state.eth1_data.deposit_count - state.eth1_deposit_index,
    )
    if len(body.deposits) != expected_deposits:
        raise ValueError(
            f"expected {expected_deposits} deposits, block has {len(body.deposits)}"
        )
    for ps in body.proposer_slashings:
        process_proposer_slashing(cfg, state, epoch_ctx, ps, verify_signatures)
    for asl in body.attester_slashings:
        process_attester_slashing(cfg, state, epoch_ctx, asl, verify_signatures)
    for att in body.attestations:
        process_attestation(cfg, state, epoch_ctx, att, verify_signatures)
    for dep in body.deposits:
        process_deposit(
            ForkName.phase0, cfg, state, dep, epoch_ctx.pubkey2index
        )
    for ex in body.voluntary_exits:
        process_voluntary_exit(cfg, state, epoch_ctx, ex, verify_signatures)


def process_block(
    cfg, state, epoch_ctx: EpochContext, block, verify_signatures: bool = True
) -> None:
    process_block_header(cfg, state, epoch_ctx, block)
    process_randao(cfg, state, epoch_ctx, block.body, verify_signatures)
    process_eth1_data(cfg, state, block.body)
    process_operations(cfg, state, epoch_ctx, block.body, verify_signatures)
