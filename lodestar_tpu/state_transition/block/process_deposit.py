"""Deposit processing (reference:
packages/state-transition/src/block/processDeposit.ts).
"""
from __future__ import annotations

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DEPOSIT_CONTRACT_TREE_DEPTH,
    DOMAIN_DEPOSIT,
    FAR_FUTURE_EPOCH,
    FORK_SEQ,
    ForkName,
)
from lodestar_tpu.types import ssz
from ..util.domain import ZERO_HASH, compute_domain, compute_signing_root
from ..util.merkle import is_valid_merkle_branch


def process_deposit(fork: ForkName, cfg, state, deposit, pubkey2index=None) -> None:
    """Apply one Deposit: verify merkle proof, then either top up an
    existing validator or add a new one after checking its proof of
    possession.  `pubkey2index` is the chain's flat pubkey cache (the
    reference's epochCtx.pubkey2index); falls back to a linear scan."""
    data = deposit.data
    if not is_valid_merkle_branch(
        ssz.phase0.DepositData.hash_tree_root(data),
        deposit.proof,
        DEPOSIT_CONTRACT_TREE_DEPTH + 1,
        state.eth1_deposit_index,
        state.eth1_data.deposit_root,
    ):
        raise ValueError("Deposit has invalid merkle proof")

    state.eth1_deposit_index += 1

    pubkey = bytes(data.pubkey)
    if pubkey2index is not None:
        index = pubkey2index.get(pubkey)
    else:
        index = next(
            (i for i, v in enumerate(state.validators) if bytes(v.pubkey) == pubkey),
            None,
        )

    if index is None or index >= len(state.validators):
        # new validator: verify the proof of possession (deposit signature)
        dm = ssz.phase0.DepositMessage(
            pubkey=data.pubkey,
            withdrawal_credentials=data.withdrawal_credentials,
            amount=data.amount,
        )
        domain = compute_domain(DOMAIN_DEPOSIT, cfg.GENESIS_FORK_VERSION, ZERO_HASH)
        signing_root = compute_signing_root(ssz.phase0.DepositMessage, dm, domain)
        try:
            pk = bls.PublicKey.from_bytes(pubkey)
            sig = bls.Signature.from_bytes(bytes(data.signature))
            if not bls.verify(pk, signing_root, sig):
                return
        except bls.BlsError:
            return

        eff = min(
            data.amount - data.amount % _p.EFFECTIVE_BALANCE_INCREMENT,
            _p.MAX_EFFECTIVE_BALANCE,
        )
        state.validators.append(
            ssz.phase0.Validator(
                pubkey=data.pubkey,
                withdrawal_credentials=data.withdrawal_credentials,
                effective_balance=eff,
                slashed=False,
                activation_eligibility_epoch=FAR_FUTURE_EPOCH,
                activation_epoch=FAR_FUTURE_EPOCH,
                exit_epoch=FAR_FUTURE_EPOCH,
                withdrawable_epoch=FAR_FUTURE_EPOCH,
            )
        )
        state.balances.append(data.amount)
        if pubkey2index is not None:
            pubkey2index[pubkey] = len(state.validators) - 1
        if FORK_SEQ[fork] >= FORK_SEQ[ForkName.altair]:
            state.inactivity_scores.append(0)
            state.previous_epoch_participation.append(0)
            state.current_epoch_participation.append(0)
    else:
        state.balances[index] += data.amount
