"""EIP-4844 block processing (reference: eip4844 branches of
packages/state-transition/src/block/index.ts; consensus-specs
eip4844/beacon-chain.md).

Adds the blob-kzg-commitments ↔ blob-transactions consistency check on top
of the capella pipeline.  KZG proof verification of the actual blobs
happens at gossip/import time against the BlobsSidecar (reference
chain/blocks flow), not in the STF.
"""
from __future__ import annotations

from typing import List, Sequence

from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    VERSIONED_HASH_VERSION_KZG,
    ForkName,
)
from ..epoch_context import EpochContext
from ..util.misc import sha256
from . import altair as ba, bellatrix as bm, capella as bc, phase0 as b0

# SSZ-typed blob transaction tag (consensus-specs eip4844 beacon-chain.md)
BLOB_TX_TYPE = 0x05
# fixed-field span of ECDSASignedBlobTransaction.message before the
# blob_versioned_hashes offset: chain_id(32) nonce(8) max_priority_fee(32)
# max_fee(32) gas(8) to_offset(4) value(32) data_offset(4)
# access_list_offset(4) max_fee_per_data_gas(32) = 188
_BLOB_HASHES_OFFSET_POS = 188


def kzg_commitment_to_versioned_hash(commitment: bytes) -> bytes:
    return bytes([VERSIONED_HASH_VERSION_KZG]) + sha256(bytes(commitment))[1:]


def tx_peek_blob_versioned_hashes(opaque_tx: bytes) -> List[bytes]:
    """Spec tx_peek_blob_versioned_hashes: offset-walk the opaque
    SSZ-serialized SignedBlobTransaction without a full decode."""
    tx = bytes(opaque_tx)
    if not tx or tx[0] != BLOB_TX_TYPE:
        raise ValueError("not a blob transaction")
    if len(tx) < 5:
        raise ValueError("truncated blob transaction")
    message_offset = 1 + int.from_bytes(tx[1:5], "little")
    pos = message_offset + _BLOB_HASHES_OFFSET_POS
    if pos + 4 > len(tx):
        raise ValueError("truncated blob transaction")
    hashes_offset = message_offset + int.from_bytes(tx[pos : pos + 4], "little")
    if (
        hashes_offset < pos + 4
        or hashes_offset > len(tx)
        or (len(tx) - hashes_offset) % 32
    ):
        raise ValueError("malformed blob transaction")
    return [tx[x : x + 32] for x in range(hashes_offset, len(tx), 32)]


def verify_kzg_commitments_against_transactions(
    transactions: Sequence[bytes], kzg_commitments: Sequence[bytes]
) -> bool:
    all_versioned_hashes: List[bytes] = []
    for tx in transactions:
        tx = bytes(tx)
        if tx and tx[0] == BLOB_TX_TYPE:
            try:
                all_versioned_hashes += tx_peek_blob_versioned_hashes(tx)
            except ValueError:
                return False
    return all_versioned_hashes == [
        kzg_commitment_to_versioned_hash(c) for c in kzg_commitments
    ]


def process_blob_kzg_commitments(cfg, state, body) -> None:
    if not hasattr(body, "execution_payload"):
        # blinded body: transactions are hidden behind transactions_root;
        # the commitment<->tx linkage is the builder's to honor and is
        # re-checked when the revealed payload is imported (reference
        # blinded flow skips this check the same way)
        return
    if not verify_kzg_commitments_against_transactions(
        list(body.execution_payload.transactions), list(body.blob_kzg_commitments)
    ):
        raise ValueError("blob kzg commitments do not match payload transactions")


def process_block(
    cfg, state, epoch_ctx: EpochContext, block, verify_signatures: bool = True,
    execution_engine=None,
) -> None:
    b0.process_block_header(cfg, state, epoch_ctx, block)
    if bm.is_execution_enabled(state, block.body):
        bc.process_withdrawals(cfg, state, bm._body_payload_or_header(block.body)[0])
        bm.process_execution_payload(cfg, state, block.body, execution_engine)
    b0.process_randao(cfg, state, epoch_ctx, block.body, verify_signatures)
    b0.process_eth1_data(cfg, state, block.body)
    bc.process_operations(
        cfg, state, epoch_ctx, block.body, verify_signatures,
        deposit_fork=ForkName.eip4844,
    )
    ba.process_sync_aggregate(cfg, state, epoch_ctx, block, verify_signatures)
    process_blob_kzg_commitments(cfg, state, block.body)
