"""Extract every signature set a block carries (reference:
packages/state-transition/src/signatureSets/index.ts:26
getBlockSignatureSets).  These sets feed the device BLS verifier in
parallel with the state transition (verifyBlock.ts:71-80).
"""
from __future__ import annotations

from typing import List

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    DOMAIN_BEACON_ATTESTER,
    DOMAIN_BEACON_PROPOSER,
    DOMAIN_RANDAO,
    DOMAIN_VOLUNTARY_EXIT,
)
from lodestar_tpu.types import ssz
from .block.phase0 import get_domain, get_indexed_attestation
from .epoch_context import EpochContext
from .util.domain import compute_signing_root
from .util.misc import compute_epoch_at_slot


def _pk(state, index: int) -> bls.PublicKey:
    return bls.PublicKey.from_bytes(bytes(state.validators[index].pubkey))


def get_block_proposer_signature_set(cfg, state, epoch_ctx, signed_block) -> bls.SignatureSet:
    block = signed_block.message
    domain = get_domain(
        cfg, state, DOMAIN_BEACON_PROPOSER, compute_epoch_at_slot(block.slot)
    )
    block_t = type(block)
    root = compute_signing_root(block_t, block, domain)
    return bls.SignatureSet(
        public_key=_pk(state, block.proposer_index),
        message=root,
        signature=bls.Signature.from_bytes(bytes(signed_block.signature)),
    )


def get_randao_signature_set(cfg, state, epoch_ctx, block) -> bls.SignatureSet:
    epoch = compute_epoch_at_slot(block.slot)
    domain = get_domain(cfg, state, DOMAIN_RANDAO, epoch)
    root = compute_signing_root(ssz.phase0.Epoch, epoch, domain)
    return bls.SignatureSet(
        public_key=_pk(state, block.proposer_index),
        message=root,
        signature=bls.Signature.from_bytes(bytes(block.body.randao_reveal)),
    )


def get_indexed_attestation_signature_set(cfg, state, indexed) -> bls.SignatureSet:
    domain = get_domain(cfg, state, DOMAIN_BEACON_ATTESTER, indexed.data.target.epoch)
    root = compute_signing_root(ssz.phase0.AttestationData, indexed.data, domain)
    pks = [_pk(state, i) for i in indexed.attesting_indices]
    return bls.SignatureSet(
        public_key=bls.aggregate_public_keys(pks),
        message=root,
        signature=bls.Signature.from_bytes(bytes(indexed.signature)),
    )


def get_attestations_signature_sets(cfg, state, epoch_ctx, block) -> List[bls.SignatureSet]:
    return [
        get_indexed_attestation_signature_set(
            cfg, state, get_indexed_attestation(epoch_ctx, att)
        )
        for att in block.body.attestations
    ]


def get_voluntary_exit_signature_set(cfg, state, signed_exit) -> bls.SignatureSet:
    domain = get_domain(cfg, state, DOMAIN_VOLUNTARY_EXIT, signed_exit.message.epoch)
    root = compute_signing_root(ssz.phase0.VoluntaryExit, signed_exit.message, domain)
    return bls.SignatureSet(
        public_key=_pk(state, signed_exit.message.validator_index),
        message=root,
        signature=bls.Signature.from_bytes(bytes(signed_exit.signature)),
    )


def get_proposer_slashing_signature_sets(cfg, state, ps) -> List[bls.SignatureSet]:
    out = []
    for signed in (ps.signed_header_1, ps.signed_header_2):
        domain = get_domain(
            cfg, state, DOMAIN_BEACON_PROPOSER, compute_epoch_at_slot(signed.message.slot)
        )
        root = compute_signing_root(ssz.phase0.BeaconBlockHeader, signed.message, domain)
        out.append(
            bls.SignatureSet(
                public_key=_pk(state, signed.message.proposer_index),
                message=root,
                signature=bls.Signature.from_bytes(bytes(signed.signature)),
            )
        )
    return out


def get_attester_slashing_signature_sets(cfg, state, asl) -> List[bls.SignatureSet]:
    return [
        get_indexed_attestation_signature_set(cfg, state, a)
        for a in (asl.attestation_1, asl.attestation_2)
    ]


def get_block_signature_sets(
    cfg,
    state,
    epoch_ctx: EpochContext,
    signed_block,
    skip_proposer_signature: bool = False,
) -> List[bls.SignatureSet]:
    """All sets in a block: proposer, randao, ops (~100+ per mainnet block
    — the load the device batch verifier is built for)."""
    block = signed_block.message
    sets: List[bls.SignatureSet] = []
    if not skip_proposer_signature:
        sets.append(
            get_block_proposer_signature_set(cfg, state, epoch_ctx, signed_block)
        )
    sets.append(get_randao_signature_set(cfg, state, epoch_ctx, block))
    for ps in block.body.proposer_slashings:
        sets.extend(get_proposer_slashing_signature_sets(cfg, state, ps))
    for asl in block.body.attester_slashings:
        sets.extend(get_attester_slashing_signature_sets(cfg, state, asl))
    sets.extend(get_attestations_signature_sets(cfg, state, epoch_ctx, block))
    for ex in block.body.voluntary_exits:
        sets.append(get_voluntary_exit_signature_set(cfg, state, ex))
    if hasattr(block.body, "sync_aggregate"):
        from .block.altair import get_sync_aggregate_signature_set

        s = get_sync_aggregate_signature_set(cfg, state, epoch_ctx, block)
        if s is not None:
            sets.append(s)
    if hasattr(block.body, "bls_to_execution_changes"):
        from .block.capella import get_bls_to_execution_change_signature_set

        for chg in block.body.bls_to_execution_changes:
            sets.append(
                get_bls_to_execution_change_signature_set(cfg, state, chg)
            )
    # deposits carry their own proof-of-possession checked inline
    # (processDeposit) because the pubkey may be brand new — same as the
    # reference (signatureSets/index.ts comment).
    return sets
