"""Altair light-client sync protocol (reference:
packages/light-client/src/ — LightClient index.ts:146,
spec/processLightClientUpdate.ts, validation.ts; consensus-specs
altair/light-client/sync-protocol.md).

A LightClient trusts one block root, initializes from a bootstrap
(current sync committee proven against the trusted header's state root),
and then follows the chain by validating LightClientUpdates: merkle
branches for finality/next-sync-committee and the sync committee's BLS
aggregate signature over the attested header.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, Optional

from lodestar_tpu.crypto.bls import api as bls
from lodestar_tpu.params import (
    ACTIVE_PRESET as _p,
    DOMAIN_SYNC_COMMITTEE,
    FINALIZED_ROOT_DEPTH,
    NEXT_SYNC_COMMITTEE_DEPTH,
)
from lodestar_tpu.state_transition.util.domain import (
    compute_domain,
    compute_signing_root,
)
from lodestar_tpu.state_transition.util.merkle import is_valid_merkle_branch
from lodestar_tpu.state_transition.util.misc import compute_epoch_at_slot
from lodestar_tpu.types import ssz

# generalized-index coordinates (validated in tests/test_light_client.py
# against ssz.proof on a real state)
FINALIZED_ROOT_INDEX = 41          # depth 6
NEXT_SYNC_COMMITTEE_INDEX = 23     # depth 5
CURRENT_SYNC_COMMITTEE_INDEX = 22  # depth 5


class LightClientError(ValueError):
    pass


def sync_period(slot: int) -> int:
    return compute_epoch_at_slot(slot) // _p.EPOCHS_PER_SYNC_COMMITTEE_PERIOD


@dataclass
class LightClientStore:
    """Spec LightClientStore."""

    finalized_header: "ssz.phase0.BeaconBlockHeader"
    current_sync_committee: "ssz.altair.SyncCommittee"
    next_sync_committee: Optional["ssz.altair.SyncCommittee"] = None
    optimistic_header: Optional["ssz.phase0.BeaconBlockHeader"] = None
    previous_max_active_participants: int = 0
    current_max_active_participants: int = 0


class LightClient:
    def __init__(self, cfg, genesis_validators_root: bytes, store: LightClientStore):
        self.cfg = cfg
        self.genesis_validators_root = genesis_validators_root
        self.store = store

    # ------------------------------------------------------------------

    @classmethod
    def initialize_from_checkpoint_root(
        cls, cfg, genesis_validators_root: bytes, trusted_block_root: bytes, bootstrap
    ) -> "LightClient":
        """Spec initialize_light_client_store: verify the bootstrap header
        matches the trusted root and the committee branch proves into its
        state root (LightClient.initializeFromCheckpointRoot)."""
        header_root = ssz.phase0.BeaconBlockHeader.hash_tree_root(bootstrap.header)
        if header_root != trusted_block_root:
            raise LightClientError("bootstrap header != trusted checkpoint root")
        leaf = ssz.altair.SyncCommittee.hash_tree_root(bootstrap.current_sync_committee)
        if not is_valid_merkle_branch(
            leaf,
            [bytes(b) for b in bootstrap.current_sync_committee_branch],
            NEXT_SYNC_COMMITTEE_DEPTH,
            CURRENT_SYNC_COMMITTEE_INDEX,
            bytes(bootstrap.header.state_root),
        ):
            raise LightClientError("invalid current sync committee branch")
        store = LightClientStore(
            finalized_header=bootstrap.header,
            current_sync_committee=bootstrap.current_sync_committee,
            optimistic_header=bootstrap.header,
        )
        return cls(cfg, genesis_validators_root, store)

    # ------------------------------------------------------------------

    def _fork_version_at(self, epoch: int) -> bytes:
        cfg = self.cfg
        if epoch >= cfg.ALTAIR_FORK_EPOCH:
            return cfg.ALTAIR_FORK_VERSION
        return cfg.GENESIS_FORK_VERSION

    def validate_update(self, update) -> None:
        """Spec validate_light_client_update."""
        store = self.store
        agg = update.sync_aggregate
        participation = sum(1 for b in agg.sync_committee_bits if b)
        if participation < _p.MIN_SYNC_COMMITTEE_PARTICIPANTS:
            raise LightClientError("insufficient sync participation")
        if not (
            update.signature_slot > update.attested_header.slot
            and update.attested_header.slot >= update.finalized_header.slot
        ):
            raise LightClientError("update slots out of order")

        store_period = sync_period(store.finalized_header.slot)
        sig_period = sync_period(update.signature_slot)
        if store.next_sync_committee is not None:
            if sig_period not in (store_period, store_period + 1):
                raise LightClientError("signature period out of range")
        elif sig_period != store_period:
            raise LightClientError("signature period != store period")

        # finality proof
        if update.finalized_header.slot != 0:
            leaf = ssz.phase0.BeaconBlockHeader.hash_tree_root(update.finalized_header)
            if not is_valid_merkle_branch(
                leaf,
                [bytes(b) for b in update.finality_branch],
                FINALIZED_ROOT_DEPTH,
                FINALIZED_ROOT_INDEX,
                bytes(update.attested_header.state_root),
            ):
                raise LightClientError("invalid finality branch")

        # next sync committee proof (against the ATTESTED state)
        if any(bytes(pk) != b"\x00" * 48 for pk in update.next_sync_committee.pubkeys):
            leaf = ssz.altair.SyncCommittee.hash_tree_root(update.next_sync_committee)
            if not is_valid_merkle_branch(
                leaf,
                [bytes(b) for b in update.next_sync_committee_branch],
                NEXT_SYNC_COMMITTEE_DEPTH,
                NEXT_SYNC_COMMITTEE_INDEX,
                bytes(update.attested_header.state_root),
            ):
                raise LightClientError("invalid next sync committee branch")

        # sync committee BLS signature over the attested header
        if sig_period == sync_period(store.finalized_header.slot):
            committee = store.current_sync_committee
        else:
            committee = store.next_sync_committee
            if committee is None:
                raise LightClientError("no next sync committee known")
        pks = [
            bls.PublicKey.from_bytes(bytes(pk))
            for pk, bit in zip(committee.pubkeys, agg.sync_committee_bits)
            if bit
        ]
        signing_epoch = compute_epoch_at_slot(max(1, update.signature_slot) - 1)
        domain = compute_domain(
            DOMAIN_SYNC_COMMITTEE,
            self._fork_version_at(signing_epoch),
            self.genesis_validators_root,
        )
        root = compute_signing_root(
            ssz.phase0.Root,
            ssz.phase0.BeaconBlockHeader.hash_tree_root(update.attested_header),
            domain,
        )
        try:
            sig = bls.Signature.from_bytes(bytes(agg.sync_committee_signature))
            ok = bls.fast_aggregate_verify(pks, root, sig)
        except ValueError as e:  # BlsError or point-decoding ValueError
            raise LightClientError(f"malformed sync committee signature: {e}")
        if not ok:
            raise LightClientError("invalid sync committee signature")

    def process_update(self, update) -> None:
        """Spec process_light_client_update (apply-if-valid, advance
        finalized/optimistic headers and committee period)."""
        self.validate_update(update)
        store = self.store
        participation = sum(1 for b in update.sync_aggregate.sync_committee_bits if b)
        store.current_max_active_participants = max(
            store.current_max_active_participants, participation
        )
        if (
            update.attested_header.slot
            > (store.optimistic_header.slot if store.optimistic_header else 0)
        ):
            store.optimistic_header = update.attested_header

        store_period = sync_period(store.finalized_header.slot)
        update_period = sync_period(update.attested_header.slot)
        has_nsc = any(
            bytes(pk) != b"\x00" * 48 for pk in update.next_sync_committee.pubkeys
        )
        if has_nsc and update_period == store_period:
            if store.next_sync_committee is None:
                store.next_sync_committee = update.next_sync_committee

        if (
            update.finalized_header.slot != 0
            and participation * 3 >= len(update.sync_aggregate.sync_committee_bits) * 2
            and update.finalized_header.slot > store.finalized_header.slot
        ):
            fin_period = sync_period(update.finalized_header.slot)
            if fin_period == store_period + 1 and store.next_sync_committee is not None:
                store.current_sync_committee = store.next_sync_committee
                store.next_sync_committee = (
                    update.next_sync_committee if has_nsc else None
                )
                store.previous_max_active_participants = (
                    store.current_max_active_participants
                )
                store.current_max_active_participants = 0
            store.finalized_header = update.finalized_header

    def process_finality_update(self, fu) -> None:
        """Accept a LightClientFinalityUpdate by lifting it into a full
        update with an empty next-sync-committee section."""
        update = ssz.altair.LightClientUpdate(
            attested_header=fu.attested_header,
            finalized_header=fu.finalized_header,
            finality_branch=list(fu.finality_branch),
            sync_aggregate=fu.sync_aggregate,
            signature_slot=fu.signature_slot,
        )
        self.process_update(update)

    def process_optimistic_update(self, ou) -> None:
        update = ssz.altair.LightClientUpdate(
            attested_header=ou.attested_header,
            sync_aggregate=ou.sync_aggregate,
            signature_slot=ou.signature_slot,
        )
        # no finality/committee sections: only the signature + slot checks
        self.validate_update(update)
        if (
            ou.attested_header.slot
            > (self.store.optimistic_header.slot if self.store.optimistic_header else 0)
        ):
            self.store.optimistic_header = ou.attested_header
