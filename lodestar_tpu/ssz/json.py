"""SSZ value <-> Beacon-API JSON (reference: @chainsafe/ssz toJson/fromJson
used by packages/api route codecs): snake_case field names, uints as
decimal strings, byte vectors/lists as 0x-hex, bitlists/bitvectors as
0x-hex of their SSZ encoding.
"""
from __future__ import annotations

from typing import Any

from .core import (
    BitlistT,
    BitvectorT,
    Boolean,
    ByteListT,
    ByteVectorT,
    ContainerMeta,
    ListT,
    SszType,
    Uint,
    VectorT,
)


def to_json(ssz_type, value) -> Any:
    if isinstance(ssz_type, Uint):
        return str(int(value))
    if isinstance(ssz_type, Boolean):
        return bool(value)
    if isinstance(ssz_type, (ByteVectorT, ByteListT)):
        return "0x" + bytes(value).hex()
    if isinstance(ssz_type, (BitlistT, BitvectorT)):
        return "0x" + ssz_type.serialize(value).hex()
    if isinstance(ssz_type, (ListT, VectorT)):
        return [to_json(ssz_type.elem, v) for v in value]
    if isinstance(ssz_type, ContainerMeta):
        return {
            name: to_json(ftype, getattr(value, name))
            for name, ftype in ssz_type._fields_.items()
        }
    raise TypeError(f"cannot JSON-encode {ssz_type!r}")


def from_json(ssz_type, data: Any):
    if isinstance(ssz_type, Uint):
        return int(data)
    if isinstance(ssz_type, Boolean):
        return bool(data) if not isinstance(data, str) else data == "true"
    if isinstance(ssz_type, (ByteVectorT, ByteListT)):
        return bytes.fromhex(data.removeprefix("0x"))
    if isinstance(ssz_type, (BitlistT, BitvectorT)):
        return ssz_type.deserialize(bytes.fromhex(data.removeprefix("0x")))
    if isinstance(ssz_type, (ListT, VectorT)):
        return [from_json(ssz_type.elem, v) for v in data]
    if isinstance(ssz_type, ContainerMeta):
        kwargs = {}
        for name, ftype in ssz_type._fields_.items():
            if name in data:
                kwargs[name] = from_json(ftype, data[name])
        return ssz_type(**kwargs)
    raise TypeError(f"cannot JSON-decode {ssz_type!r}")
