from .core import (  # noqa: F401
    Bitlist, BitlistT, Bitvector, BitvectorT, ByteList, ByteListT, ByteVector,
    ByteVectorT, Bytes4, Bytes20, Bytes32, Bytes48, Bytes96, Container,
    ContainerMeta, List, ListT, SszType, Uint, Vector, VectorT, ZERO_HASHES,
    boolean, hash_nodes, merkleize_chunks, mix_in_length, pack_bytes,
    uint8, uint16, uint32, uint64, uint128, uint256,
)
