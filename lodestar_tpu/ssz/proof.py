"""SSZ merkle proofs over container field paths (reference:
@chainsafe/persistent-merkle-tree getSingleProof +
beacon-node/src/chain/lightClient/proofs.ts).

The light-client protocol needs branches for state fields
(current/next_sync_committee, finalized_checkpoint.root) against the
state root.  Proofs compose bottom-up along a field path: the generalized
index is the concatenation of each level's (depth, index) pair and the
branch is inner-first sibling hashes — exactly what
is_valid_merkle_branch consumes.
"""
from __future__ import annotations

from typing import List, Sequence, Tuple

from lodestar_tpu.state_transition.util.merkle import list_tree_layers
from .core import ContainerMeta, ZERO_HASHES, merkleize_chunks


def _container_depth(n_fields: int) -> int:
    limit = 1 if n_fields <= 1 else 1 << (n_fields - 1).bit_length()
    return limit.bit_length() - 1


def _single_level_proof(
    cls: ContainerMeta, value, field: str
) -> Tuple[bytes, List[bytes], int, int]:
    """(leaf, branch, depth, index) for one container field."""
    names = list(cls._fields_.keys())
    index = names.index(field)
    leaves = cls.field_roots(value)
    depth = _container_depth(len(leaves))
    layers = list_tree_layers(leaves, depth)
    branch = []
    idx = index
    for level in range(depth):
        sib = idx ^ 1
        layer = layers[level]
        branch.append(layer[sib] if sib < len(layer) else ZERO_HASHES[level])
        idx >>= 1
    return leaves[index], branch, depth, index


def container_field_proof(
    cls: ContainerMeta, value, path: Sequence[str]
) -> Tuple[bytes, List[bytes], int, int]:
    """Proof of the subtree at `path` (outermost field first) against
    ``cls.hash_tree_root(value)``.

    Returns (leaf, branch, depth, index) where branch is bottom-up —
    verify with is_valid_merkle_branch(leaf, branch, depth, index, root).
    """
    if not path:
        raise ValueError("empty path")
    # walk down to the innermost container, collecting per-level proofs
    levels = []  # (leaf, branch, depth, index) outermost-first
    cur_cls, cur_val = cls, value
    for field in path:
        leaf, branch, depth, index = _single_level_proof(cur_cls, cur_val, field)
        levels.append((leaf, branch, depth, index))
        t = cur_cls._fields_[field]
        if isinstance(t, ContainerMeta):
            cur_cls, cur_val = t, getattr(cur_val, field)
        else:
            cur_cls, cur_val = None, getattr(cur_val, field)

    # compose bottom-up: innermost branch first
    leaf = levels[-1][0]
    branch: List[bytes] = []
    depth = 0
    index = 0
    for lvl_leaf, lvl_branch, lvl_depth, lvl_index in reversed(levels):
        branch.extend(lvl_branch)
        index |= lvl_index << depth
        depth += lvl_depth
    return leaf, branch, depth, index
