"""SSZ (SimpleSerialize) engine: serialization + merkleization.

The TPU-native rebuild's equivalent of the reference's `@chainsafe/ssz` +
`@chainsafe/persistent-merkle-tree` + `@chainsafe/as-sha256` stack (consumed
via packages/types/src/sszTypes.ts).  Values are plain Python objects (ints,
bytes, lists, Container instances) rather than tree-backed views: the
state-transition layer keeps its own flat numpy caches for the O(V) hot
loops (mirroring the reference's EpochContext design,
state-transition/src/cache/epochContext.ts:80), so the tree is only needed
for hashTreeRoot and proofs — computed here with a layer-wise numpy+hashlib
merkleizer and a zero-subtree cache.

Spec: consensus-specs/ssz/simple-serialize.md (v1.3.0-alpha.2 era, matching
the reference's spec-test pin, test/spec/specTestVersioning.ts:17).
"""
from __future__ import annotations

import hashlib
from typing import Any, Dict, List as PyList, Optional, Sequence, Tuple

from lodestar_tpu import native as _native

BYTES_PER_CHUNK = 32
ZERO_CHUNK = b"\x00" * BYTES_PER_CHUNK

# zero_hashes[i] = root of a depth-i all-zero subtree
ZERO_HASHES: PyList[bytes] = [ZERO_CHUNK]
for _ in range(64):
    ZERO_HASHES.append(
        hashlib.sha256(ZERO_HASHES[-1] + ZERO_HASHES[-1]).digest()
    )

_NATIVE = _native.available()


def hash_nodes(a: bytes, b: bytes) -> bytes:
    return hashlib.sha256(a + b).digest()


def _next_pow2(n: int) -> int:
    if n <= 1:
        return 1
    return 1 << (n - 1).bit_length()


def merkleize_chunks(chunks: Sequence[bytes], limit: Optional[int] = None) -> bytes:
    """Merkle root of chunks padded with zero-subtrees to `limit` leaves.

    limit=None pads to the next power of two of len(chunks)."""
    count = len(chunks)
    if limit is None:
        limit = _next_pow2(count)
    else:
        if count > limit:
            raise ValueError(f"chunk count {count} exceeds limit {limit}")
        limit = _next_pow2(limit)
    if limit == 1:
        return bytes(chunks[0]) if count else ZERO_CHUNK
    depth = limit.bit_length() - 1
    if count == 0:
        return ZERO_HASHES[depth]
    if _NATIVE:
        # one native call per layer (the as-sha256 batched-hash role)
        buf = b"".join(bytes(c) for c in chunks)
        for level in range(depth):
            buf = _native.hash_layer(buf, ZERO_HASHES[level])
        return buf
    layer = [bytes(c) for c in chunks]
    for level in range(depth):
        nxt = []
        for i in range(0, len(layer) - 1, 2):
            nxt.append(hash_nodes(layer[i], layer[i + 1]))
        if len(layer) % 2:
            nxt.append(hash_nodes(layer[-1], ZERO_HASHES[level]))
        layer = nxt
    return layer[0]


def mix_in_length(root: bytes, length: int) -> bytes:
    return hash_nodes(root, length.to_bytes(32, "little"))


def pack_bytes(data: bytes) -> PyList[bytes]:
    """Right-pad to a whole number of 32-byte chunks."""
    if not data:
        return []
    n = len(data)
    rem = n % BYTES_PER_CHUNK
    if rem:
        data = data + b"\x00" * (BYTES_PER_CHUNK - rem)
    return [data[i : i + BYTES_PER_CHUNK] for i in range(0, len(data), BYTES_PER_CHUNK)]


# ---------------------------------------------------------------------------
# type descriptors
# ---------------------------------------------------------------------------


class SszType:
    """Base type descriptor.  Subclasses implement the SSZ spec quartet."""

    def is_fixed(self) -> bool:
        raise NotImplementedError

    def fixed_size(self) -> int:
        raise NotImplementedError

    def default(self):
        raise NotImplementedError

    def serialize(self, value) -> bytes:
        raise NotImplementedError

    def deserialize(self, data: bytes):
        raise NotImplementedError

    def hash_tree_root(self, value) -> bytes:
        raise NotImplementedError

    # chunk count for List limits — overridden per spec category
    def __repr__(self):
        return self.__class__.__name__


class Uint(SszType):
    def __init__(self, bits: int):
        assert bits in (8, 16, 32, 64, 128, 256)
        self.bits = bits
        self.nbytes = bits // 8

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.nbytes

    def default(self):
        return 0

    def serialize(self, value) -> bytes:
        return int(value).to_bytes(self.nbytes, "little")

    def deserialize(self, data: bytes):
        if len(data) != self.nbytes:
            raise ValueError("bad uint size")
        return int.from_bytes(data, "little")

    def hash_tree_root(self, value) -> bytes:
        return self.serialize(value).ljust(32, b"\x00")

    def __repr__(self):
        return f"uint{self.bits}"


class Boolean(SszType):
    def is_fixed(self):
        return True

    def fixed_size(self):
        return 1

    def default(self):
        return False

    def serialize(self, value) -> bytes:
        return b"\x01" if value else b"\x00"

    def deserialize(self, data: bytes):
        if data == b"\x01":
            return True
        if data == b"\x00":
            return False
        raise ValueError("bad boolean")

    def hash_tree_root(self, value) -> bytes:
        return self.serialize(value).ljust(32, b"\x00")


class ByteVectorT(SszType):
    def __init__(self, length: int):
        self.length = length

    def is_fixed(self):
        return True

    def fixed_size(self):
        return self.length

    def default(self):
        return b"\x00" * self.length

    def serialize(self, value) -> bytes:
        value = bytes(value)
        if len(value) != self.length:
            raise ValueError(f"ByteVector[{self.length}] got {len(value)} bytes")
        return value

    def deserialize(self, data: bytes):
        return self.serialize(data)

    def hash_tree_root(self, value) -> bytes:
        return merkleize_chunks(pack_bytes(self.serialize(value)))

    def __repr__(self):
        return f"ByteVector[{self.length}]"


class ByteListT(SszType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed(self):
        return False

    def default(self):
        return b""

    def serialize(self, value) -> bytes:
        value = bytes(value)
        if len(value) > self.limit:
            raise ValueError("ByteList over limit")
        return value

    def deserialize(self, data: bytes):
        return self.serialize(data)

    def hash_tree_root(self, value) -> bytes:
        value = bytes(value)
        limit_chunks = (self.limit + 31) // 32
        return mix_in_length(
            merkleize_chunks(pack_bytes(value), limit_chunks), len(value)
        )

    def __repr__(self):
        return f"ByteList[{self.limit}]"


class BitvectorT(SszType):
    def __init__(self, length: int):
        assert length > 0
        self.length = length

    def is_fixed(self):
        return True

    def fixed_size(self):
        return (self.length + 7) // 8

    def default(self):
        return [False] * self.length

    def _to_bytes(self, bits) -> bytes:
        if len(bits) != self.length:
            raise ValueError("Bitvector length mismatch")
        out = bytearray((self.length + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        return bytes(out)

    def serialize(self, bits) -> bytes:
        return self._to_bytes(bits)

    def deserialize(self, data: bytes):
        if len(data) != self.fixed_size():
            raise ValueError("bad Bitvector size")
        bits = [bool((data[i // 8] >> (i % 8)) & 1) for i in range(self.length)]
        # excess bits in the last byte must be zero
        for i in range(self.length, len(data) * 8):
            if (data[i // 8] >> (i % 8)) & 1:
                raise ValueError("Bitvector high bits set")
        return bits

    def hash_tree_root(self, bits) -> bytes:
        return merkleize_chunks(
            pack_bytes(self._to_bytes(bits)), (self.length + 255) // 256
        )

    def __repr__(self):
        return f"Bitvector[{self.length}]"


class BitlistT(SszType):
    def __init__(self, limit: int):
        self.limit = limit

    def is_fixed(self):
        return False

    def default(self):
        return []

    def serialize(self, bits) -> bytes:
        if len(bits) > self.limit:
            raise ValueError("Bitlist over limit")
        n = len(bits)
        out = bytearray(n // 8 + 1)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        out[n // 8] |= 1 << (n % 8)  # delimiter bit
        return bytes(out)

    def deserialize(self, data: bytes):
        if not data or data[-1] == 0:
            raise ValueError("Bitlist missing delimiter")
        last = data[-1]
        hi = last.bit_length() - 1
        n = (len(data) - 1) * 8 + hi
        if n > self.limit:
            raise ValueError("Bitlist over limit")
        bits = []
        for i in range(n):
            bits.append(bool((data[i // 8] >> (i % 8)) & 1))
        return bits

    def hash_tree_root(self, bits) -> bytes:
        n = len(bits)
        out = bytearray((n + 7) // 8)
        for i, b in enumerate(bits):
            if b:
                out[i // 8] |= 1 << (i % 8)
        limit_chunks = (self.limit + 255) // 256
        return mix_in_length(merkleize_chunks(pack_bytes(bytes(out)), limit_chunks), n)

    def __repr__(self):
        return f"Bitlist[{self.limit}]"


def _is_basic(t: SszType) -> bool:
    return isinstance(t, (Uint, Boolean))


class VectorT(SszType):
    def __init__(self, elem: SszType, length: int):
        assert length > 0
        self.elem = elem
        self.length = length

    def is_fixed(self):
        return self.elem.is_fixed()

    def fixed_size(self):
        return self.elem.fixed_size() * self.length

    def default(self):
        return [self.elem.default() for _ in range(self.length)]

    def serialize(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("Vector length mismatch")
        return _serialize_sequence(self.elem, value)

    def deserialize(self, data: bytes):
        out = _deserialize_sequence(self.elem, data)
        if len(out) != self.length:
            raise ValueError("Vector length mismatch")
        return out

    def hash_tree_root(self, value) -> bytes:
        if len(value) != self.length:
            raise ValueError("Vector length mismatch")
        if _is_basic(self.elem):
            data = b"".join(self.elem.serialize(v) for v in value)
            return merkleize_chunks(pack_bytes(data))
        roots = [self.elem.hash_tree_root(v) for v in value]
        return merkleize_chunks(roots)

    def __repr__(self):
        return f"Vector[{self.elem!r}, {self.length}]"


class ListT(SszType):
    def __init__(self, elem: SszType, limit: int):
        self.elem = elem
        self.limit = limit

    def is_fixed(self):
        return False

    def default(self):
        return []

    def serialize(self, value) -> bytes:
        if len(value) > self.limit:
            raise ValueError("List over limit")
        return _serialize_sequence(self.elem, value)

    def deserialize(self, data: bytes):
        out = _deserialize_sequence(self.elem, data)
        if len(out) > self.limit:
            raise ValueError("List over limit")
        return out

    def hash_tree_root(self, value) -> bytes:
        if _is_basic(self.elem):
            data = b"".join(self.elem.serialize(v) for v in value)
            limit_chunks = (self.limit * self.elem.fixed_size() + 31) // 32
            root = merkleize_chunks(pack_bytes(data), limit_chunks)
        else:
            roots = [self.elem.hash_tree_root(v) for v in value]
            root = merkleize_chunks(roots, self.limit)
        return mix_in_length(root, len(value))

    def __repr__(self):
        return f"List[{self.elem!r}, {self.limit}]"


def _serialize_sequence(elem: SszType, value) -> bytes:
    if elem.is_fixed():
        return b"".join(elem.serialize(v) for v in value)
    parts = [elem.serialize(v) for v in value]
    offset = 4 * len(parts)
    out = bytearray()
    for p in parts:
        out += offset.to_bytes(4, "little")
        offset += len(p)
    for p in parts:
        out += p
    return bytes(out)


def _deserialize_sequence(elem: SszType, data: bytes):
    if elem.is_fixed():
        sz = elem.fixed_size()
        if sz == 0:
            raise ValueError("zero-size element")
        if len(data) % sz:
            raise ValueError("sequence size not a multiple of element size")
        return [elem.deserialize(data[i : i + sz]) for i in range(0, len(data), sz)]
    if not data:
        return []
    first_off = int.from_bytes(data[0:4], "little")
    if first_off % 4 or first_off > len(data):
        raise ValueError("bad first offset")
    n = first_off // 4
    offs = [int.from_bytes(data[4 * i : 4 * i + 4], "little") for i in range(n)]
    offs.append(len(data))
    out = []
    for i in range(n):
        if offs[i] > offs[i + 1]:
            raise ValueError("offsets not monotonic")
        out.append(elem.deserialize(data[offs[i] : offs[i + 1]]))
    return out


# ---------------------------------------------------------------------------
# containers
# ---------------------------------------------------------------------------


class ContainerMeta(type):
    def __new__(mcls, name, bases, ns):
        cls = super().__new__(mcls, name, bases, ns)
        fields: Dict[str, SszType] = {}
        for b in bases:
            fields.update(getattr(b, "_fields_", {}))
        for fname, ftype in ns.get("__annotations__", {}).items():
            if isinstance(ftype, SszType):
                fields[fname] = ftype
            elif isinstance(ftype, ContainerMeta):
                fields[fname] = ftype  # nested container class doubles as type
            elif isinstance(ftype, str) and not fname.startswith("_"):
                raise TypeError(
                    f"{name}.{fname}: annotation is a string — the defining "
                    "module must NOT use `from __future__ import annotations`"
                )
        cls._fields_ = fields
        # Per-object root caching soundness class: every field holds an
        # IMMUTABLE Python value (int/bool/bytes), so the only way the
        # root can change is a field assignment — which __setattr__
        # version-bumps.  Validator, Checkpoint, Fork, Eth1Data,
        # BeaconBlockHeader... all qualify; anything holding a list or
        # nested container does not (inner mutation bypasses the bump).
        cls._shallow_fixed_ = bool(fields) and all(
            isinstance(t, (Uint, Boolean, ByteVectorT)) for t in fields.values()
        )
        # frozen classes (set _frozen_ = True in the class body) are
        # immutable records: field writes raise, copy() returns self, and
        # the root is cached on the instance forever.
        cls._frozen_ = bool(ns.get("_frozen_", getattr(cls, "_frozen_", False)))
        return cls

    # container classes themselves act as SszType descriptors -------------
    def is_fixed(cls) -> bool:
        return all(t.is_fixed() for t in cls._fields_.values())

    def fixed_size(cls) -> int:
        return sum(t.fixed_size() for t in cls._fields_.values())

    def default(cls):
        return cls(**{n: t.default() for n, t in cls._fields_.items()})

    def serialize(cls, value) -> bytes:
        fixed_parts = []
        var_parts = []
        for n, t in cls._fields_.items():
            v = getattr(value, n)
            if t.is_fixed():
                fixed_parts.append(t.serialize(v))
            else:
                fixed_parts.append(None)
                var_parts.append(t.serialize(v))
        fixed_len = sum(len(p) if p is not None else 4 for p in fixed_parts)
        out = bytearray()
        offset = fixed_len
        vi = 0
        for p in fixed_parts:
            if p is None:
                out += offset.to_bytes(4, "little")
                offset += len(var_parts[vi])
                vi += 1
            else:
                out += p
        for p in var_parts:
            out += p
        return bytes(out)

    def deserialize(cls, data: bytes):
        kwargs = {}
        pos = 0
        var_fields = []
        offsets = []
        for n, t in cls._fields_.items():
            if t.is_fixed():
                sz = t.fixed_size()
                if pos + sz > len(data):
                    raise ValueError("container truncated")
                kwargs[n] = t.deserialize(data[pos : pos + sz])
                pos += sz
            else:
                offsets.append(int.from_bytes(data[pos : pos + 4], "little"))
                var_fields.append((n, t))
                pos += 4
        if not var_fields:
            if pos != len(data):
                raise ValueError("container has trailing bytes")
            return cls(**kwargs)
        # first offset must point exactly at the end of the fixed part
        if offsets[0] != pos:
            raise ValueError("bad first container offset")
        offsets.append(len(data))
        for i, (n, t) in enumerate(var_fields):
            if offsets[i] > offsets[i + 1]:
                raise ValueError("container offsets not monotonic")
            kwargs[n] = t.deserialize(data[offsets[i] : offsets[i + 1]])
        return cls(**kwargs)

    def hash_tree_root(cls, value) -> bytes:
        # Layered caching (the rebuild's answer to the reference's
        # tree-backed views, stateCache.ts:30-110):
        #   1. frozen records (Validator): root cached on the instance
        #      forever — an unchanged validator costs one attr read.
        #   2. shallow-fixed mutable containers (Checkpoint, Eth1Data,
        #      BeaconBlockHeader...): root cached per (instance, version);
        #      __setattr__ bumps the version.
        #   3. value-keyed memo for small fixed containers: dedups across
        #      object identities (deserialized copies of the same record).
        #   4. big list/vector FIELDS: incremental layer caches — see
        #      field_roots + ssz/incremental.py.
        if cls._frozen_:
            root = value.__dict__.get("_htr_")
            if root is None:
                root = cls._root_compute(value)
                object.__setattr__(value, "_htr_", root)
            return root
        if cls._shallow_fixed_:
            ver = value.__dict__.get("_v_", 0)
            ent = value.__dict__.get("_htr_")
            if ent is not None and ent[0] == ver:
                return ent[1]
            root = cls._root_compute(value)
            object.__setattr__(value, "_htr_", (ver, root))
            return root
        return merkleize_chunks(cls.field_roots(value))

    def _root_compute(cls, value) -> bytes:
        """Root via the value-keyed memo (shared across instances)."""
        cache = cls.__dict__.get("_root_memo_")
        if cache is None:
            small_fixed = cls.is_fixed() and cls.fixed_size() <= 256
            cache = {} if small_fixed else False
            cls._root_memo_ = cache
            if cache is not False:
                # entry budget sized off real per-entry cost (key bytes +
                # CPython bytes/dict overhead ~3x+200B): ~64 MB true RSS
                # per class, ~115k Validator records
                cls._root_memo_cap_ = max(
                    1 << 14,
                    (64 << 20) // (3 * max(1, cls.fixed_size()) + 200),
                )
        if cache is False:
            return merkleize_chunks(cls.field_roots(value))
        key = cls.serialize(value)
        root = cache.get(key)
        if root is None:
            root = merkleize_chunks(cls.field_roots(value))
            # FREEZE when full rather than evict: full-state hashing
            # scans the registry in the same order every time, so any
            # eviction policy (FIFO/LRU) thrashes to ~0% hits once the
            # live set exceeds the cap — keeping the first cap entries
            # guarantees a cap/N hit rate and never makes hashing
            # slower than uncached (miss cost = one serialize+lookup)
            if len(cache) < cls._root_memo_cap_:
                cache[key] = root
        return root

    def field_roots(cls, value) -> PyList[bytes]:
        """Per-field subtree roots — the container's merkle leaves (used
        by ssz/proof.py for light-client branches).

        Heavy list/vector fields (state.validators, balances, ...) are
        lazily wrapped in a TrackedList here so their roots come from the
        incremental layer cache (ssz/incremental.py) — per-block state
        hashing is O(changed leaves), matching the reference's persistent
        tree (stateCache.ts:30)."""
        from . import incremental as _inc

        if cls._frozen_:
            # frozen records cache their WHOLE root on the instance
            # (hash_tree_root above) — wrapping their fields would swap
            # the immutable tuples installed by __init__ for mutable
            # lists, breaking the frozen invariant and __eq__
            return [t.hash_tree_root(getattr(value, n)) for n, t in cls._fields_.items()]
        roots = []
        for n, t in cls._fields_.items():
            v = getattr(value, n)
            if isinstance(v, _inc.TrackedList):
                if v._stype_ is not t:
                    v = _inc.ensure_tracked(value, n, t, v)
                roots.append(_inc.commit(v))
            elif isinstance(t, (ListT, VectorT)) and _inc.is_heavy(t, v):
                roots.append(_inc.commit(_inc.ensure_tracked(value, n, t, v)))
            else:
                roots.append(t.hash_tree_root(v))
        return roots


class Container(metaclass=ContainerMeta):
    """Value base class; subclass with annotated fields (SszType instances).

    The subclass is simultaneously the value class and the type descriptor
    (classmethod serialize/deserialize/hash_tree_root/default)."""

    _fields_: Dict[str, SszType] = {}

    def __init__(self, **kwargs):
        frozen = type(self)._frozen_
        for n, t in type(self)._fields_.items():
            if n in kwargs:
                v = kwargs.pop(n)
            else:
                v = t.default()
            if frozen and isinstance(v, list):
                # freeze list-valued fields too so per-object root caching
                # is sound (nothing reachable from a frozen record mutates)
                v = tuple(v)
            object.__setattr__(self, n, v)
        if kwargs:
            raise TypeError(f"unknown fields: {sorted(kwargs)}")

    def __setattr__(self, name, value):
        cls = type(self)
        if cls._frozen_:
            raise AttributeError(
                f"{cls.__name__} is frozen — build a new record with "
                f".replace({name}=...) instead"
            )
        if name not in cls._fields_:
            raise AttributeError(f"{cls.__name__} has no SSZ field {name!r}")
        object.__setattr__(self, name, value)
        # version bump backing the per-object root cache (shallow-fixed
        # classes); harmless elsewhere
        object.__setattr__(self, "_v_", self.__dict__.get("_v_", 0) + 1)

    def replace(self, **kwargs):
        """New record with the given fields replaced (the mutation API for
        frozen containers; works on any container)."""
        fields = {n: getattr(self, n) for n in type(self)._fields_}
        unknown = set(kwargs) - set(fields)
        if unknown:
            raise TypeError(f"unknown fields: {sorted(unknown)}")
        fields.update(kwargs)
        return type(self)(**fields)

    def copy(self):
        """Value copy with structural sharing where sound: frozen records
        (and lists of them) are shared, tracked lists share their
        committed merkle layers, mutable nested containers are copied.
        The per-block state clone (state_transition.py:121) rides this —
        the reference gets the same from persistent-tree views
        (stateCache.ts)."""
        if type(self)._frozen_:
            return self
        from . import incremental as _inc

        kwargs = {}
        for n, t in type(self)._fields_.items():
            v = getattr(self, n)
            if isinstance(v, Container):
                v = v.copy()
            elif isinstance(v, (list, _inc.TrackedList)):
                # element sharing is sound when elements are immutable:
                # basic values, bytes, frozen records — the common case
                # (validators, balances); only mutable container elements
                # need copying
                elem = getattr(t, "elem", None)
                share = not isinstance(elem, ContainerMeta) or elem._frozen_
                if isinstance(v, _inc.TrackedList):
                    tl = v.copy_tracked()
                    if not share:
                        for i, e in enumerate(tl):
                            if isinstance(e, Container) and not type(e)._frozen_:
                                # same value ⇒ same root: bypass tracking
                                list.__setitem__(tl, i, e.copy())
                    v = tl
                elif share:
                    v = list(v)
                else:
                    v = [
                        e.copy()
                        if isinstance(e, Container) and not type(e)._frozen_
                        else e
                        for e in v
                    ]
            kwargs[n] = v
        new = type(self)(**kwargs)
        # carry a current per-object root across the copy (same value ⇒
        # same root; fresh object starts at version 0)
        ent = self.__dict__.get("_htr_")
        if ent is not None and type(self)._shallow_fixed_:
            if ent[0] == self.__dict__.get("_v_", 0):
                object.__setattr__(new, "_htr_", (0, ent[1]))
        return new

    def __eq__(self, other):
        if type(self) is not type(other):
            return NotImplemented
        return all(
            getattr(self, n) == getattr(other, n) for n in type(self)._fields_
        )

    def __repr__(self):
        inner = ", ".join(f"{n}={getattr(self, n)!r}" for n in type(self)._fields_)
        return f"{type(self).__name__}({inner})"


# convenient aliases ---------------------------------------------------------

uint8 = Uint(8)
uint16 = Uint(16)
uint32 = Uint(32)
uint64 = Uint(64)
uint128 = Uint(128)
uint256 = Uint(256)
boolean = Boolean()


class _Indexable:
    """Vector[elem, N] / List[elem, N] / ... sugar."""

    def __init__(self, ctor, name):
        self.ctor = ctor
        self.name = name

    def __getitem__(self, args):
        if not isinstance(args, tuple):
            args = (args,)
        return self.ctor(*args)

    def __repr__(self):
        return self.name


def _vec(elem, n):
    return VectorT(elem, n)


def _lst(elem, n):
    return ListT(elem, n)


Vector = _Indexable(_vec, "Vector")
List = _Indexable(_lst, "List")
Bitvector = _Indexable(BitvectorT, "Bitvector")
Bitlist = _Indexable(BitlistT, "Bitlist")
ByteVector = _Indexable(ByteVectorT, "ByteVector")
ByteList = _Indexable(ByteListT, "ByteList")

Bytes4 = ByteVectorT(4)
Bytes20 = ByteVectorT(20)
Bytes32 = ByteVectorT(32)
Bytes48 = ByteVectorT(48)
Bytes96 = ByteVectorT(96)
