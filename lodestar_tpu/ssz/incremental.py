"""Incremental merkleization: O(changed-leaves · log N) hashTreeRoot.

The reference gets per-block O(changes) state hashing from tree-backed
views with structural sharing (@chainsafe/persistent-merkle-tree, consumed
through state-transition/src/cache/stateCache.ts:30-110 — its design doc
pins the ceilings the block budget assumes).  The rebuild keeps plain
Python values (flat numpy epoch caches do the O(V) work instead of tree
views), so the equivalent here is a LIST-LEVEL incremental merkleizer:

- `TrackedList` — a drop-in `list` subclass that records which indices
  were written (`state.balances[i] = x`, `state.validators[i] = v`,
  `append`) since the last commit.  Any structural operation it cannot
  attribute to indices (slice write, sort, ...) just flags a full
  rebuild — correctness never depends on the tracking being complete.
- `LayerStack` — the committed merkle layers of one list, an IMMUTABLE
  snapshot.  `Container.copy()` shares it between the pre- and
  post-state (structural sharing across the per-block clone in
  state_transition.py:121); each copy accumulates its own dirty set and
  the commit copy-on-writes only the layers it patches.
- `commit()` — recomputes exactly the dirty chunks' root-paths with one
  batched native sha256 call per level (ls_hash_pairs), or falls back to
  a full layer-wise rebuild (ls_hash_layer) when most of the list
  changed (e.g. the per-epoch balance update).

Per-element roots for container/byte-vector elements come from the
per-object root caches in ssz/core.py (frozen Validator records cache
their root forever; shallow-fixed mutable containers cache per version),
so an unchanged validator costs one attribute read, not a serialize.

Spec: consensus-specs/ssz/simple-serialize.md merkleization; equivalence
with the from-scratch `merkleize_chunks` is asserted by differential
tests (tests/test_incremental_merkle.py).
"""
from __future__ import annotations

from typing import List as PyList, Optional, Sequence, Set

import numpy as np

from lodestar_tpu import native as _native

from . import core as _core

ZERO_HASHES = _core.ZERO_HASHES
_NATIVE = _native.available()

# lists whose merkleization is at least this many chunks get a tracked
# wrapper + layer cache on first hash; smaller ones stay on the direct path
HEAVY_MIN_CHUNKS = 64


def _hash_pairs_np(pairs: np.ndarray) -> np.ndarray:
    """(k, 64) uint8 -> (k, 32) uint8 parent nodes."""
    k = pairs.shape[0]
    if _NATIVE:
        out = _native.hash_pairs(pairs.tobytes())
        return np.frombuffer(out, dtype=np.uint8).reshape(k, 32)
    import hashlib

    out = np.empty((k, 32), dtype=np.uint8)
    buf = pairs.tobytes()
    for i in range(k):
        out[i] = np.frombuffer(
            hashlib.sha256(buf[64 * i : 64 * i + 64]).digest(), dtype=np.uint8
        )
    return out


def _hash_layer_np(layer: np.ndarray, level: int) -> np.ndarray:
    """(n, 32) uint8 -> (ceil(n/2), 32); odd tail paired with the zero hash."""
    n = layer.shape[0]
    if _NATIVE:
        out = _native.hash_layer(layer.tobytes(), ZERO_HASHES[level])
        return np.frombuffer(out, dtype=np.uint8).reshape((n + 1) // 2, 32).copy()
    if n % 2:
        layer = np.concatenate(
            [layer, np.frombuffer(ZERO_HASHES[level], dtype=np.uint8)[None, :]]
        )
    return _hash_pairs_np(layer.reshape(-1, 64))


class LayerStack:
    """Committed merkle layers of one list's chunk leaves (immutable).

    layers[0] is the (count, 32) leaf array; layers[k+1] has
    ceil(len(layers[k])/2) rows; the last layer has a single row — the
    root of the next_pow2(count)-leaf occupied subtree.  Shared between
    state copies; commit() builds a NEW stack, copy-on-writing only the
    arrays it patches.
    """

    __slots__ = ("layers", "count")

    def __init__(self, layers: PyList[np.ndarray], count: int):
        self.layers = layers
        self.count = count

    @staticmethod
    def build(leaves: np.ndarray) -> "LayerStack":
        """Full layer-wise rebuild from a (n, 32) uint8 leaf array."""
        n = leaves.shape[0]
        layers = [leaves]
        level = 0
        cur = leaves
        while cur.shape[0] > 1:
            cur = _hash_layer_np(cur, level)
            layers.append(cur)
            level += 1
        return LayerStack(layers, n)

    def subtree_root(self) -> bytes:
        if self.count == 0:
            return _core.ZERO_CHUNK
        return self.layers[-1][0].tobytes()

    def subtree_depth(self) -> int:
        return len(self.layers) - 1

    def patch(self, leaves: np.ndarray, dirty: Sequence[int]) -> "LayerStack":
        """New stack with `dirty` leaf rows replaced / appended.

        `leaves` is the FULL new (n, 32) leaf array (n >= self.count is a
        grow, dirty must cover the appended rows); only dirty root-paths
        are rehashed, one batched native call per level.
        """
        n = leaves.shape[0]
        depth = max(1, _core._next_pow2(n)).bit_length() - 1
        new_layers: PyList[np.ndarray] = [leaves]
        dirty_idx = np.unique(np.asarray(sorted(dirty), dtype=np.int64))
        cur = leaves
        for level in range(depth):
            parents = np.unique(dirty_idx >> 1)
            below = cur
            nb = below.shape[0]
            left = below[np.minimum(parents * 2, nb - 1)]
            right_i = parents * 2 + 1
            in_range = right_i < nb
            right = below[np.minimum(right_i, nb - 1)].copy()
            if not in_range.all():
                right[~in_range] = np.frombuffer(ZERO_HASHES[level], dtype=np.uint8)
            pairs = np.concatenate([left, right], axis=1)
            hashed = _hash_pairs_np(pairs)
            n_up = (nb + 1) // 2
            if level + 1 < len(self.layers) and self.layers[level + 1].shape[0] == n_up:
                up = self.layers[level + 1].copy()
            else:
                old = (
                    self.layers[level + 1]
                    if level + 1 < len(self.layers)
                    else np.empty((0, 32), dtype=np.uint8)
                )
                up = np.empty((n_up, 32), dtype=np.uint8)
                m = min(old.shape[0], n_up)
                up[:m] = old[:m]
            up[parents] = hashed
            new_layers.append(up)
            dirty_idx = parents
            cur = up
        return LayerStack(new_layers, n)


def _chain_to_limit(root: bytes, occupied_depth: int, limit_depth: int) -> bytes:
    for level in range(occupied_depth, limit_depth):
        root = _core.hash_nodes(root, ZERO_HASHES[level])
    return root


class TrackedList(list):
    """list subclass recording written indices for incremental HTR.

    Wrapped lazily by ContainerMeta.field_roots when a field's
    merkleization is heavy; every STF mutation path (index write, append)
    lands here because the wrapper IS the field value.  Operations that
    cannot be mapped to indices set `_force_` and the next commit
    rebuilds — tracking completeness is a performance property only.
    """

    __slots__ = ("_dirty_", "_snap_", "_stype_", "_force_", "_clen_")

    def __init__(self, *args):
        super().__init__(*args)
        self._dirty_: Set[int] = set()
        self._snap_: Optional[LayerStack] = None
        self._stype_ = None
        self._force_ = False
        self._clen_ = 0  # element count at last commit (appends extend past it)

    # -- tracked mutations -------------------------------------------------
    def __setitem__(self, i, v):
        if isinstance(i, slice):
            self._force_ = True
        else:
            if i < 0:
                i += len(self)
            self._dirty_.add(i)
        super().__setitem__(i, v)

    # append/extend need no bookkeeping: commit treats rows past the
    # committed count as dirty by construction

    def __delitem__(self, i):
        self._force_ = True
        super().__delitem__(i)

    def insert(self, i, v):
        self._force_ = True
        super().insert(i, v)

    def pop(self, i=-1):
        self._force_ = True
        return super().pop(i)

    def remove(self, v):
        self._force_ = True
        super().remove(v)

    def clear(self):
        self._force_ = True
        super().clear()

    def reverse(self):
        self._force_ = True
        super().reverse()

    def sort(self, **kw):
        self._force_ = True
        super().sort(**kw)

    def __imul__(self, n):
        self._force_ = True
        return super().__imul__(n)

    def copy_tracked(self) -> "TrackedList":
        """Value copy sharing the committed layer snapshot (structural
        sharing across the per-block state clone)."""
        new = TrackedList(self)
        new._snap_ = self._snap_
        new._stype_ = self._stype_
        new._force_ = self._force_
        new._dirty_ = set(self._dirty_)
        new._clen_ = self._clen_
        return new


# -- leaf encoding ----------------------------------------------------------


def _basic_chunk_bytes(stype, values, start_chunk: int, end_chunk: int) -> bytes:
    """Serialized chunks [start, end) of a basic-element sequence."""
    elem = stype.elem
    size = elem.fixed_size()
    per = 32 // size
    lo = start_chunk * per
    hi = min(len(values), end_chunk * per)
    if size == 8:
        arr = np.array(values[lo:hi], dtype="<u8")
    elif size == 1:
        arr = np.array(values[lo:hi], dtype=np.uint8)
    else:
        data = b"".join(elem.serialize(v) for v in values[lo:hi])
        arr = np.frombuffer(data, dtype=np.uint8)
    buf = arr.tobytes()
    want = (end_chunk - start_chunk) * 32
    if len(buf) < want:
        buf += b"\x00" * (want - len(buf))
    return buf


def _leaf_array(stype, values) -> np.ndarray:
    """Full (nchunks, 32) uint8 leaf array for the current values."""
    elem = stype.elem
    if _core._is_basic(elem):
        per = 32 // elem.fixed_size()
        nchunks = (len(values) + per - 1) // per
        buf = _basic_chunk_bytes(stype, values, 0, nchunks)
        return np.frombuffer(buf, dtype=np.uint8).reshape(nchunks, 32).copy()
    if isinstance(elem, _core.ByteVectorT) and elem.length == 32:
        if len(values) == 0:
            return np.empty((0, 32), dtype=np.uint8)
        buf = b"".join(bytes(v) for v in values)
        return np.frombuffer(buf, dtype=np.uint8).reshape(len(values), 32).copy()
    roots = b"".join(elem.hash_tree_root(v) for v in values)
    out = np.frombuffer(roots, dtype=np.uint8)
    return out.reshape(len(values), 32).copy() if len(values) else np.empty((0, 32), dtype=np.uint8)


def _elem_root(stype, v) -> bytes:
    elem = stype.elem
    if isinstance(elem, _core.ByteVectorT) and elem.length == 32:
        return bytes(v)
    return elem.hash_tree_root(v)


def _limit_chunks(stype) -> int:
    """Padded leaf-count ceiling of the type's merkleization."""
    elem = stype.elem
    if isinstance(stype, _core.ListT):
        if _core._is_basic(elem):
            return _core._next_pow2((stype.limit * elem.fixed_size() + 31) // 32)
        return _core._next_pow2(stype.limit)
    # Vector: padded to next_pow2 of its own chunk count
    if _core._is_basic(elem):
        return _core._next_pow2((stype.length * elem.fixed_size() + 31) // 32)
    return _core._next_pow2(stype.length)


def is_heavy(stype, value) -> bool:
    """Wrap-worthy?  Fixed-element list/vector whose CURRENT merkleization
    is at least HEAVY_MIN_CHUNKS chunks, with elements the tracker can
    treat as values: basic ints/bools, byte vectors, or FROZEN containers.
    Mutable container elements (Eth1Data, ...) can change in place without
    the list seeing a dirty index — those stay on the direct path, and
    variable-size elements change their own chunk footprint in place."""
    if not isinstance(stype, (_core.ListT, _core.VectorT)):
        return False
    elem = stype.elem
    if not elem.is_fixed():
        return False
    if isinstance(elem, _core.ContainerMeta) and not elem._frozen_:
        return False
    if _core._is_basic(elem):
        per = 32 // elem.fixed_size() if elem.fixed_size() <= 32 else 1
        nchunks = (len(value) + per - 1) // per if per else len(value)
    else:
        nchunks = len(value)
    return nchunks >= HEAVY_MIN_CHUNKS


def commit(tl: TrackedList) -> bytes:
    """Root of the tracked list, patching the committed snapshot."""
    stype = tl._stype_
    elem = stype.elem
    basic = _core._is_basic(elem)
    per = (32 // elem.fixed_size()) if basic else 1
    n = len(tl)
    nchunks = (n + per - 1) // per
    snap = tl._snap_

    rebuild = (
        snap is None
        or tl._force_
        or snap.count == 0
        or nchunks < snap.count
    )
    if not rebuild:
        dirty_chunks = {i // per for i in tl._dirty_ if i // per < snap.count}
        # appends since the last commit: every chunk from the one holding
        # the old tail element onward (a partially-filled tail chunk
        # changes content when elements pack into it)
        dirty_chunks.update(range(min(tl._clen_ // per, snap.count), nchunks))
        if len(dirty_chunks) * max(1, snap.subtree_depth()) >= max(64, nchunks):
            rebuild = True
    if rebuild:
        stack = LayerStack.build(_leaf_array(stype, tl))
    elif not dirty_chunks:
        stack = snap
    else:
        leaves = snap.layers[0]
        if nchunks != snap.count:
            grown = np.empty((nchunks, 32), dtype=np.uint8)
            grown[: snap.count] = leaves
            leaves = grown
        else:
            leaves = leaves.copy()
        if basic:
            for c in dirty_chunks:
                leaves[c] = np.frombuffer(
                    _basic_chunk_bytes(stype, tl, c, c + 1), dtype=np.uint8
                )
        else:
            for c in dirty_chunks:
                leaves[c] = np.frombuffer(_elem_root(stype, tl[c]), dtype=np.uint8)
        stack = snap.patch(leaves, sorted(dirty_chunks))

    tl._snap_ = stack
    tl._dirty_.clear()
    tl._force_ = False
    tl._clen_ = n

    limit = _limit_chunks(stype)
    limit_depth = max(0, limit.bit_length() - 1)
    root = _chain_to_limit(stack.subtree_root(), stack.subtree_depth(), limit_depth)
    if isinstance(stype, _core.ListT):
        root = _core.mix_in_length(root, n)
    return root


def ensure_tracked(container, name: str, stype, value) -> TrackedList:
    """Wrap `container.name` in a TrackedList bound to its SSZ type."""
    if isinstance(value, TrackedList) and value._stype_ is stype:
        return value
    tl = TrackedList(value)
    tl._stype_ = stype
    object.__setattr__(container, name, tl)
    return tl
