/* lodestar_tpu native runtime kernels.
 *
 * TPU-native rebuild of the reference's native/WASM host dependencies
 * (SURVEY §2.3): @chainsafe/as-sha256 (SSZ merkleization hashing),
 * xxhash-wasm (gossip fast message ids), @chainsafe/snappy-stream /
 * snappyjs (gossip + reqresp compression, CRC-32C framing checksums).
 *
 * Single translation unit, no external dependencies; built as a shared
 * library at first import (lodestar_tpu/native/__init__.py) and bound
 * with ctypes.  All entry points are plain C ABI.
 */

#include <stddef.h>
#include <stdint.h>
#include <string.h>

#if defined(_MSC_VER)
#define LS_EXPORT __declspec(dllexport)
#else
#define LS_EXPORT __attribute__((visibility("default")))
#endif

/* ================================================================== */
/* SHA-256 (FIPS 180-4)                                               */
/* ================================================================== */

static const uint32_t K256[64] = {
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2};

#define ROTR(x, n) (((x) >> (n)) | ((x) << (32 - (n))))
#define SHR(x, n) ((x) >> (n))
#define CH(x, y, z) (((x) & (y)) ^ (~(x) & (z)))
#define MAJ(x, y, z) (((x) & (y)) ^ ((x) & (z)) ^ ((y) & (z)))
#define BSIG0(x) (ROTR(x, 2) ^ ROTR(x, 13) ^ ROTR(x, 22))
#define BSIG1(x) (ROTR(x, 6) ^ ROTR(x, 11) ^ ROTR(x, 25))
#define SSIG0(x) (ROTR(x, 7) ^ ROTR(x, 18) ^ SHR(x, 3))
#define SSIG1(x) (ROTR(x, 17) ^ ROTR(x, 19) ^ SHR(x, 10))

static const uint32_t H256_INIT[8] = {0x6a09e667, 0xbb67ae85, 0x3c6ef372,
                                      0xa54ff53a, 0x510e527f, 0x9b05688c,
                                      0x1f83d9ab, 0x5be0cd19};

static inline uint32_t load_be32(const uint8_t *p) {
  return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
         ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static inline void store_be32(uint8_t *p, uint32_t v) {
  p[0] = (uint8_t)(v >> 24);
  p[1] = (uint8_t)(v >> 16);
  p[2] = (uint8_t)(v >> 8);
  p[3] = (uint8_t)v;
}

static void sha256_compress(uint32_t st[8], const uint8_t block[64]) {
  uint32_t w[64];
  uint32_t a, b, c, d, e, f, g, h, t1, t2;
  int i;
  for (i = 0; i < 16; i++) w[i] = load_be32(block + 4 * i);
  for (i = 16; i < 64; i++)
    w[i] = SSIG1(w[i - 2]) + w[i - 7] + SSIG0(w[i - 15]) + w[i - 16];
  a = st[0]; b = st[1]; c = st[2]; d = st[3];
  e = st[4]; f = st[5]; g = st[6]; h = st[7];
  for (i = 0; i < 64; i++) {
    t1 = h + BSIG1(e) + CH(e, f, g) + K256[i] + w[i];
    t2 = BSIG0(a) + MAJ(a, b, c);
    h = g; g = f; f = e; e = d + t1;
    d = c; c = b; b = a; a = t1 + t2;
  }
  st[0] += a; st[1] += b; st[2] += c; st[3] += d;
  st[4] += e; st[5] += f; st[6] += g; st[7] += h;
}

LS_EXPORT void ls_sha256(const uint8_t *data, size_t len, uint8_t out[32]) {
  uint32_t st[8];
  uint8_t block[64];
  size_t i, rem;
  uint64_t bitlen = (uint64_t)len * 8;
  memcpy(st, H256_INIT, sizeof(st));
  for (i = 0; i + 64 <= len; i += 64) sha256_compress(st, data + i);
  rem = len - i;
  memset(block, 0, 64);
  memcpy(block, data + i, rem);
  block[rem] = 0x80;
  if (rem >= 56) {
    sha256_compress(st, block);
    memset(block, 0, 64);
  }
  for (i = 0; i < 8; i++) block[56 + i] = (uint8_t)(bitlen >> (56 - 8 * i));
  sha256_compress(st, block);
  for (i = 0; i < 8; i++) store_be32(out + 4 * i, st[i]);
}

/* The merkleization workhorse: hash n pairs of 32-byte nodes (64-byte
 * messages).  The second (padding) block is constant for 64-byte input:
 * 0x80, zeros, bitlen 512. */
LS_EXPORT void ls_hash_pairs(const uint8_t *in, uint8_t *out, size_t n) {
  static uint8_t pad[64];
  uint32_t st[8];
  size_t k;
  int i;
  pad[0] = 0x80;
  pad[62] = 0x02; /* 512 bits big-endian -> bytes 62,63 = 0x02,0x00 */
  for (k = 0; k < n; k++) {
    memcpy(st, H256_INIT, sizeof(st));
    sha256_compress(st, in + 64 * k);
    sha256_compress(st, pad);
    for (i = 0; i < 8; i++) store_be32(out + 32 * k + 4 * i, st[i]);
  }
}

/* Hash a merkle layer of n nodes into ceil(n/2) nodes; odd tail is paired
 * with `zero` (the zero-subtree hash of this level). */
LS_EXPORT void ls_hash_layer(const uint8_t *in, size_t n, const uint8_t zero[32],
                             uint8_t *out) {
  size_t pairs = n / 2;
  ls_hash_pairs(in, out, pairs);
  if (n % 2) {
    uint8_t buf[64];
    memcpy(buf, in + 64 * pairs, 32);
    memcpy(buf + 32, zero, 32);
    ls_hash_pairs(buf, out + 32 * pairs, 1);
  }
}

/* ================================================================== */
/* xxHash64 (xxhash.com reference algorithm)                          */
/* ================================================================== */

#define P1 0x9E3779B185EBCA87ULL
#define P2 0xC2B2AE3D27D4EB4FULL
#define P3 0x165667B19E3779F9ULL
#define P4 0x85EBCA77C2B2AE63ULL
#define P5 0x27D4EB2F165667C5ULL

static inline uint64_t rotl64(uint64_t x, int r) {
  return (x << r) | (x >> (64 - r));
}

static inline uint64_t load_le64(const uint8_t *p) {
  uint64_t v;
  memcpy(&v, p, 8);
  return v; /* little-endian hosts only (x86/arm) */
}

static inline uint32_t load_le32(const uint8_t *p) {
  uint32_t v;
  memcpy(&v, p, 4);
  return v;
}

static inline uint64_t xxh_round(uint64_t acc, uint64_t input) {
  acc += input * P2;
  acc = rotl64(acc, 31);
  acc *= P1;
  return acc;
}

static inline uint64_t xxh_merge(uint64_t acc, uint64_t val) {
  val = xxh_round(0, val);
  acc ^= val;
  acc = acc * P1 + P4;
  return acc;
}

LS_EXPORT uint64_t ls_xxh64(const uint8_t *p, size_t len, uint64_t seed) {
  const uint8_t *end = p + len;
  uint64_t h;
  if (len >= 32) {
    uint64_t v1 = seed + P1 + P2, v2 = seed + P2, v3 = seed, v4 = seed - P1;
    const uint8_t *limit = end - 32;
    do {
      v1 = xxh_round(v1, load_le64(p)); p += 8;
      v2 = xxh_round(v2, load_le64(p)); p += 8;
      v3 = xxh_round(v3, load_le64(p)); p += 8;
      v4 = xxh_round(v4, load_le64(p)); p += 8;
    } while (p <= limit);
    h = rotl64(v1, 1) + rotl64(v2, 7) + rotl64(v3, 12) + rotl64(v4, 18);
    h = xxh_merge(h, v1);
    h = xxh_merge(h, v2);
    h = xxh_merge(h, v3);
    h = xxh_merge(h, v4);
  } else {
    h = seed + P5;
  }
  h += (uint64_t)len;
  while (p + 8 <= end) {
    h ^= xxh_round(0, load_le64(p));
    h = rotl64(h, 27) * P1 + P4;
    p += 8;
  }
  if (p + 4 <= end) {
    h ^= (uint64_t)load_le32(p) * P1;
    h = rotl64(h, 23) * P2 + P3;
    p += 4;
  }
  while (p < end) {
    h ^= (*p) * P5;
    h = rotl64(h, 11) * P1;
    p++;
  }
  h ^= h >> 33;
  h *= P2;
  h ^= h >> 29;
  h *= P3;
  h ^= h >> 32;
  return h;
}

/* ================================================================== */
/* CRC-32C (Castagnoli, for snappy framing masked checksums)          */
/* ================================================================== */

static uint32_t crc32c_table[256];
static int crc32c_ready = 0;

static void crc32c_init(void) {
  uint32_t i, j, crc;
  for (i = 0; i < 256; i++) {
    crc = i;
    for (j = 0; j < 8; j++)
      crc = (crc >> 1) ^ (0x82F63B78U & (~(crc & 1) + 1));
    crc32c_table[i] = crc;
  }
  crc32c_ready = 1;
}

LS_EXPORT uint32_t ls_crc32c(const uint8_t *p, size_t len) {
  uint32_t crc = 0xFFFFFFFFU;
  size_t i;
  if (!crc32c_ready) crc32c_init();
  for (i = 0; i < len; i++)
    crc = (crc >> 8) ^ crc32c_table[(crc ^ p[i]) & 0xFF];
  return crc ^ 0xFFFFFFFFU;
}

/* ================================================================== */
/* Snappy raw block format (format_description.txt)                   */
/* ================================================================== */

static size_t write_uvarint(uint8_t *out, uint64_t n) {
  size_t i = 0;
  while (n >= 0x80) {
    out[i++] = (uint8_t)(n | 0x80);
    n >>= 7;
  }
  out[i++] = (uint8_t)n;
  return i;
}

LS_EXPORT size_t ls_snappy_max_compressed(size_t n) {
  return 32 + n + n / 6;
}

#define HASH_BITS 14
#define HASH_SIZE (1u << HASH_BITS)

static inline uint32_t snappy_hash(uint32_t v) {
  return (v * 0x1e35a7bdU) >> (32 - HASH_BITS);
}

static uint8_t *emit_literal(uint8_t *op, const uint8_t *lit, size_t len) {
  size_t n = len - 1;
  if (n < 60) {
    *op++ = (uint8_t)(n << 2);
  } else if (n < 256) {
    *op++ = 60 << 2;
    *op++ = (uint8_t)n;
  } else if (n < 65536) {
    *op++ = 61 << 2;
    *op++ = (uint8_t)n;
    *op++ = (uint8_t)(n >> 8);
  } else if (n < (1u << 24)) {
    *op++ = 62 << 2;
    *op++ = (uint8_t)n;
    *op++ = (uint8_t)(n >> 8);
    *op++ = (uint8_t)(n >> 16);
  } else {
    *op++ = 63 << 2;
    *op++ = (uint8_t)n;
    *op++ = (uint8_t)(n >> 8);
    *op++ = (uint8_t)(n >> 16);
    *op++ = (uint8_t)(n >> 24);
  }
  memcpy(op, lit, len);
  return op + len;
}

static uint8_t *emit_copy_upto64(uint8_t *op, size_t offset, size_t len) {
  if (len >= 4 && len <= 11 && offset < 2048) {
    *op++ = (uint8_t)(1 | ((len - 4) << 2) | ((offset >> 8) << 5));
    *op++ = (uint8_t)offset;
  } else {
    *op++ = (uint8_t)(2 | ((len - 1) << 2));
    *op++ = (uint8_t)offset;
    *op++ = (uint8_t)(offset >> 8);
  }
  return op;
}

static uint8_t *emit_copy(uint8_t *op, size_t offset, size_t len) {
  while (len >= 68) {
    op = emit_copy_upto64(op, offset, 64);
    len -= 64;
  }
  if (len > 64) {
    op = emit_copy_upto64(op, offset, 60);
    len -= 60;
  }
  return emit_copy_upto64(op, offset, len);
}

LS_EXPORT long ls_snappy_compress(const uint8_t *in, size_t n, uint8_t *out) {
  uint16_t table[HASH_SIZE];
  uint8_t *op = out;
  size_t ip = 0, lit_start = 0, block_start;
  op += write_uvarint(op, n);
  /* process in 64 KiB blocks so 16-bit table offsets suffice */
  for (block_start = 0; block_start < n; block_start += 65536) {
    size_t block_end = block_start + 65536 < n ? block_start + 65536 : n;
    memset(table, 0, sizeof(table));
    ip = block_start;
    lit_start = block_start;
    if (block_end - block_start >= 15) {
      while (ip + 4 <= block_end) {
        uint32_t v = load_le32(in + ip);
        uint32_t h = snappy_hash(v);
        size_t cand = block_start + table[h];
        table[h] = (uint16_t)(ip - block_start);
        if (cand < ip && load_le32(in + cand) == v) {
          size_t len = 4;
          while (ip + len < block_end && in[cand + len] == in[ip + len]) len++;
          if (ip > lit_start)
            op = emit_literal(op, in + lit_start, ip - lit_start);
          op = emit_copy(op, ip - cand, len);
          ip += len;
          lit_start = ip;
        } else {
          ip++;
        }
      }
    }
    if (block_end > lit_start)
      op = emit_literal(op, in + lit_start, block_end - lit_start);
  }
  if (n == 0) { /* empty input: just the varint 0 */ }
  return (long)(op - out);
}

static int read_uvarint(const uint8_t *in, size_t n, size_t *pos, uint64_t *out) {
  uint64_t result = 0;
  int shift = 0;
  while (*pos < n) {
    uint8_t b = in[(*pos)++];
    result |= (uint64_t)(b & 0x7F) << shift;
    if (!(b & 0x80)) {
      *out = result;
      return 0;
    }
    shift += 7;
    if (shift > 63) return -1;
  }
  return -1;
}

LS_EXPORT long ls_snappy_uncompressed_length(const uint8_t *in, size_t n) {
  size_t pos = 0;
  uint64_t len;
  if (read_uvarint(in, n, &pos, &len) != 0) return -1;
  return (long)len;
}

LS_EXPORT long ls_snappy_uncompress(const uint8_t *in, size_t n, uint8_t *out,
                                    size_t cap) {
  size_t pos = 0, op = 0;
  uint64_t expect;
  if (read_uvarint(in, n, &pos, &expect) != 0) return -1;
  if (expect > cap) return -1;
  while (pos < n) {
    uint8_t tag = in[pos++];
    uint32_t kind = tag & 3;
    if (kind == 0) { /* literal */
      size_t len = (tag >> 2) + 1;
      if (len > 60) {
        size_t nb = len - 60, i;
        if (pos + nb > n) return -1;
        len = 0;
        for (i = 0; i < nb; i++) len |= (size_t)in[pos + i] << (8 * i);
        len += 1;
        pos += nb;
      }
      if (pos + len > n || op + len > expect) return -1;
      memcpy(out + op, in + pos, len);
      pos += len;
      op += len;
    } else {
      size_t len, offset;
      if (kind == 1) {
        if (pos >= n) return -1;
        len = ((tag >> 2) & 7) + 4;
        offset = ((size_t)(tag >> 5) << 8) | in[pos++];
      } else if (kind == 2) {
        if (pos + 2 > n) return -1;
        len = (tag >> 2) + 1;
        offset = (size_t)in[pos] | ((size_t)in[pos + 1] << 8);
        pos += 2;
      } else {
        if (pos + 4 > n) return -1;
        len = (tag >> 2) + 1;
        offset = (size_t)in[pos] | ((size_t)in[pos + 1] << 8) |
                 ((size_t)in[pos + 2] << 16) | ((size_t)in[pos + 3] << 24);
        pos += 4;
      }
      if (offset == 0 || offset > op || op + len > expect) return -1;
      {
        size_t i; /* byte-wise: copies may overlap forward (RLE) */
        for (i = 0; i < len; i++) out[op + i] = out[op + i - offset];
      }
      op += len;
    }
  }
  if (op != expect) return -1;
  return (long)op;
}
