/* RFC 9380 hash-to-curve for BLS12-381 G2 — native host fast path.
 *
 * Role parity: the reference client gets hash_to_g2 natively inside blst
 * (consumed via @chainsafe/bls at packages/beacon-node/src/chain/bls/);
 * this file fills that role for the rebuild.  The pure-Python oracle
 * (lodestar_tpu/crypto/bls/hash_to_curve.py) costs ~65 ms per message —
 * three orders of magnitude off the per-attestation budget; this C path
 * is differential-tested against it (tests/test_native_h2c.py) and
 * against the RFC 9380 BLS12381G2_XMD:SHA-256_SSWU_RO_ vectors.
 *
 * Field arithmetic: 6x64-bit limbs, Montgomery form (R = 2^384), CIOS
 * multiplication with __uint128_t.  All curve/isogeny constants are
 * GENERATED from the Python oracle (tools/gen_h2c_constants.py) — no
 * hand transcription.
 *
 * Pipeline (mirrors the oracle function-for-function):
 *   expand_message_xmd(SHA-256)            [ls_sha256 from lodestar_native.c]
 *   -> hash_to_field(Fp2, count=2)
 *   -> simplified SWU on E'' (branching variant, like the oracle)
 *   -> 3-isogeny to E'
 *   -> clear_cofactor (Budroni-Pintore psi form)
 *   -> affine output (plain big-endian bytes)
 */
#include <stddef.h>
#include <stdint.h>
#include <string.h>

#include "bls_h2c_constants.h"

#if defined(_MSC_VER)
#define LS_EXPORT __declspec(dllexport)
#else
#define LS_EXPORT __attribute__((visibility("default")))
#endif

typedef unsigned __int128 u128;

extern void ls_sha256(const uint8_t *data, size_t len, uint8_t out[32]);

/* ------------------------------------------------------------------ */
/* Fp: 6x64 little-endian limbs, Montgomery form                       */
/* ------------------------------------------------------------------ */

static const fp FP_ZERO = {{0, 0, 0, 0, 0, 0}};

static int fp_is_zero(const fp *a) {
  uint64_t acc = 0;
  for (int i = 0; i < 6; i++) acc |= a->v[i];
  return acc == 0;
}

static int fp_eq(const fp *a, const fp *b) {
  uint64_t acc = 0;
  for (int i = 0; i < 6; i++) acc |= a->v[i] ^ b->v[i];
  return acc == 0;
}

static int fp_ge_p(const fp *a) {
  for (int i = 5; i >= 0; i--) {
    if (a->v[i] > FP_P.v[i]) return 1;
    if (a->v[i] < FP_P.v[i]) return 0;
  }
  return 1; /* equal */
}

static void fp_sub_p(fp *a) {
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a->v[i] - FP_P.v[i] - (uint64_t)borrow;
    a->v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
}

static void fp_add_(fp *r, const fp *a, const fp *b) {
  u128 carry = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)a->v[i] + b->v[i] + (uint64_t)carry;
    r->v[i] = (uint64_t)s;
    carry = s >> 64;
  }
  /* a, b < p < 2^381 so no carry out of limb 5 */
  if (fp_ge_p(r)) fp_sub_p(r);
}

static void fp_sub_(fp *r, const fp *a, const fp *b) {
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)a->v[i] - b->v[i] - (uint64_t)borrow;
    r->v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  if (borrow) { /* r += p */
    u128 carry = 0;
    for (int i = 0; i < 6; i++) {
      u128 s = (u128)r->v[i] + FP_P.v[i] + (uint64_t)carry;
      r->v[i] = (uint64_t)s;
      carry = s >> 64;
    }
  }
}

static void fp_neg_(fp *r, const fp *a) {
  if (fp_is_zero(a)) { *r = FP_ZERO; return; }
  u128 borrow = 0;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)FP_P.v[i] - a->v[i] - (uint64_t)borrow;
    r->v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
}

/* CIOS Montgomery multiplication: r = a*b*R^-1 mod p, canonical out. */
static void fp_mul_(fp *r, const fp *a, const fp *b) {
  uint64_t t[8];
  memset(t, 0, sizeof(t));
  for (int i = 0; i < 6; i++) {
    u128 c = 0;
    uint64_t ai = a->v[i];
    for (int j = 0; j < 6; j++) {
      u128 s = (u128)t[j] + (u128)ai * b->v[j] + (uint64_t)c;
      t[j] = (uint64_t)s;
      c = s >> 64;
    }
    u128 s = (u128)t[6] + (uint64_t)c;
    t[6] = (uint64_t)s;
    t[7] = (uint64_t)(s >> 64);

    uint64_t m = t[0] * FP_N0INV;
    c = ((u128)t[0] + (u128)m * FP_P.v[0]) >> 64;
    for (int j = 1; j < 6; j++) {
      s = (u128)t[j] + (u128)m * FP_P.v[j] + (uint64_t)c;
      t[j - 1] = (uint64_t)s;
      c = s >> 64;
    }
    s = (u128)t[6] + (uint64_t)c;
    t[5] = (uint64_t)s;
    t[6] = t[7] + (uint64_t)(s >> 64);
    t[7] = 0;
  }
  memcpy(r->v, t, 6 * sizeof(uint64_t));
  if (t[6] || fp_ge_p(r)) fp_sub_p(r);
}

/* SOS Montgomery reduction of a 12-limb product (t[12] spare carry) */
static void mont_reduce12(fp *r, uint64_t t[13]) {
  for (int i = 0; i < 6; i++) {
    uint64_t m = t[i] * FP_N0INV;
    u128 c = 0;
    for (int j = 0; j < 6; j++) {
      u128 s = (u128)t[i + j] + (u128)m * FP_P.v[j] + (uint64_t)c;
      t[i + j] = (uint64_t)s;
      c = s >> 64;
    }
    int k = i + 6;
    while (c) {
      u128 s = (u128)t[k] + (uint64_t)c;
      t[k] = (uint64_t)s;
      c = s >> 64;
      k++;
    }
  }
  memcpy(r->v, t + 6, 6 * sizeof(uint64_t));
  if (t[12] || fp_ge_p(r)) fp_sub_p(r);
}

/* Dedicated squaring (SOS with doubled cross terms): the pow chains are
 * ~85% squarings, worth ~35% of their multiplies. */
static void fp_sqr_(fp *r, const fp *a) {
  uint64_t t[13];
  memset(t, 0, sizeof(t));
  for (int i = 0; i < 6; i++) {
    u128 c = 0;
    for (int j = i + 1; j < 6; j++) {
      u128 s = (u128)t[i + j] + (u128)a->v[i] * a->v[j] + (uint64_t)c;
      t[i + j] = (uint64_t)s;
      c = s >> 64;
    }
    t[i + 6] = (uint64_t)c;
  }
  uint64_t carry = 0;
  for (int k = 1; k < 12; k++) {
    uint64_t hi = t[k] >> 63;
    t[k] = (t[k] << 1) | carry;
    carry = hi;
  }
  u128 c = 0;
  for (int i = 0; i < 6; i++) {
    u128 s = (u128)t[2 * i] + (u128)a->v[i] * a->v[i] + (uint64_t)c;
    t[2 * i] = (uint64_t)s;
    u128 s2 = (u128)t[2 * i + 1] + (uint64_t)(s >> 64);
    t[2 * i + 1] = (uint64_t)s2;
    c = s2 >> 64;
  }
  mont_reduce12(r, t);
}

static void fp_from_mont(fp *r, const fp *a) {
  fp one = {{1, 0, 0, 0, 0, 0}};
  fp_mul_(r, a, &one);
}

/* a^e, e given as 6 plain limbs (fits: all exponents used are < p). */
static void fp_pow_(fp *r, const fp *a, const fp *e) {
  fp table[16];
  table[0] = FP_ONE_M;
  table[1] = *a;
  for (int i = 2; i < 16; i++) fp_mul_(&table[i], &table[i - 1], a);
  fp acc = FP_ONE_M;
  int started = 0;
  for (int i = 95; i >= 0; i--) {
    unsigned ni = (unsigned)((e->v[i / 16] >> ((i % 16) * 4)) & 0xF);
    if (!started && !ni) continue; /* skip leading zero nibbles */
    if (started)
      for (int k = 0; k < 4; k++) fp_sqr_(&acc, &acc);
    if (ni) fp_mul_(&acc, &acc, &table[ni]);
    started = 1;
  }
  *r = acc;
}

/* p-2 (for Fermat inversion), computed once */
static fp FP_P_MINUS_2;
/* (p-1)/2 and (p-3)/4 == p>>2 (p = 3 mod 4), as plain limb exponents */
static fp FP_P_HALF, FP_P_34;
static fp FP_MINUS_ONE_M; /* -1 in Montgomery form */
static int h2c_ready = 0;

static void fp_shr(fp *r, const fp *a, int k) {
  for (int i = 0; i < 6; i++) {
    uint64_t lo = a->v[i] >> k;
    uint64_t hi = (i + 1 < 6) ? (a->v[i + 1] << (64 - k)) : 0;
    r->v[i] = lo | hi;
  }
}

static void h2c_init(void) {
  if (h2c_ready) return;
  u128 borrow = 2;
  for (int i = 0; i < 6; i++) {
    u128 d = (u128)FP_P.v[i] - (uint64_t)borrow;
    FP_P_MINUS_2.v[i] = (uint64_t)d;
    borrow = (d >> 64) & 1;
  }
  fp_shr(&FP_P_HALF, &FP_P, 1);
  fp_shr(&FP_P_34, &FP_P, 2);
  fp_neg_(&FP_MINUS_ONE_M, &FP_ONE_M);
  h2c_ready = 1;
}

static void fp_inv_(fp *r, const fp *a) { fp_pow_(r, a, &FP_P_MINUS_2); }

/* ------------------------------------------------------------------ */
/* Fp2 = Fp[u] / (u^2 + 1)                                             */
/* ------------------------------------------------------------------ */

static void f2_add_(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_add_(&r->c0, &a->c0, &b->c0);
  fp_add_(&r->c1, &a->c1, &b->c1);
}

static void f2_sub_(fp2 *r, const fp2 *a, const fp2 *b) {
  fp_sub_(&r->c0, &a->c0, &b->c0);
  fp_sub_(&r->c1, &a->c1, &b->c1);
}

static void f2_neg_(fp2 *r, const fp2 *a) {
  fp_neg_(&r->c0, &a->c0);
  fp_neg_(&r->c1, &a->c1);
}

static void f2_conj_(fp2 *r, const fp2 *a) {
  r->c0 = a->c0;
  fp_neg_(&r->c1, &a->c1);
}

static int f2_is_zero(const fp2 *a) {
  return fp_is_zero(&a->c0) && fp_is_zero(&a->c1);
}

static int f2_eq(const fp2 *a, const fp2 *b) {
  return fp_eq(&a->c0, &b->c0) && fp_eq(&a->c1, &b->c1);
}

/* Karatsuba: 3 Fp products */
static void f2_mul_(fp2 *r, const fp2 *a, const fp2 *b) {
  fp t0, t1, sa, sb, t2;
  fp_mul_(&t0, &a->c0, &b->c0);
  fp_mul_(&t1, &a->c1, &b->c1);
  fp_add_(&sa, &a->c0, &a->c1);
  fp_add_(&sb, &b->c0, &b->c1);
  fp_mul_(&t2, &sa, &sb);
  fp_sub_(&r->c0, &t0, &t1);
  fp_sub_(&t2, &t2, &t0);
  fp_sub_(&r->c1, &t2, &t1);
}

static void f2_sqr_(fp2 *r, const fp2 *a) {
  fp s, d, t;
  fp_add_(&s, &a->c0, &a->c1);
  fp_sub_(&d, &a->c0, &a->c1);
  fp_mul_(&t, &a->c0, &a->c1);
  fp_mul_(&r->c0, &s, &d);
  fp_add_(&r->c1, &t, &t);
}

static void f2_inv_(fp2 *r, const fp2 *a) {
  fp n0, n1, norm, ninv;
  fp_sqr_(&n0, &a->c0);
  fp_sqr_(&n1, &a->c1);
  fp_add_(&norm, &n0, &n1);
  fp_inv_(&ninv, &norm);
  fp_mul_(&r->c0, &a->c0, &ninv);
  fp neg1;
  fp_neg_(&neg1, &a->c1);
  fp_mul_(&r->c1, &neg1, &ninv);
}

/* a^e for a plain-limb exponent e (bits scanned over all 384) */
static void f2_pow_(fp2 *r, const fp2 *a, const fp *e) {
  fp2 table[16];
  table[0].c0 = FP_ONE_M;
  table[0].c1 = FP_ZERO;
  table[1] = *a;
  for (int i = 2; i < 16; i++) f2_mul_(&table[i], &table[i - 1], a);
  fp2 acc = table[0];
  int started = 0;
  for (int i = 95; i >= 0; i--) {
    unsigned ni = (unsigned)((e->v[i / 16] >> ((i % 16) * 4)) & 0xF);
    if (!started && !ni) continue;
    if (started)
      for (int k = 0; k < 4; k++) f2_sqr_(&acc, &acc);
    if (ni) f2_mul_(&acc, &acc, &table[ni]);
    started = 1;
  }
  *r = acc;
}

/* RFC 9380 sgn0 on Fp2 (parity of the canonical integer, conditioned) */
static int f2_sgn0(const fp2 *a) {
  fp p0, p1;
  fp_from_mont(&p0, &a->c0);
  fp_from_mont(&p1, &a->c1);
  int sign_0 = (int)(p0.v[0] & 1);
  int zero_0 = fp_is_zero(&p0);
  int sign_1 = (int)(p1.v[0] & 1);
  return sign_0 | (zero_0 & sign_1);
}

/* Square root in Fp2, Adj-Rodriguez for p = 3 mod 4 (mirrors oracle
 * f2_sqrt).  Returns 0 if `a` is a non-residue. */
static int f2_sqrt_(fp2 *r, const fp2 *a) {
  if (f2_is_zero(a)) { r->c0 = FP_ZERO; r->c1 = FP_ZERO; return 1; }
  fp2 a1, x0, alpha, x;
  f2_pow_(&a1, a, &FP_P_34);        /* a^((p-3)/4) */
  f2_mul_(&x0, &a1, a);             /* a^((p+1)/4) */
  f2_mul_(&alpha, &a1, &x0);        /* a^((p-1)/2) */
  if (fp_eq(&alpha.c0, &FP_MINUS_ONE_M) && fp_is_zero(&alpha.c1)) {
    /* x = u * x0 = (-x0.c1, x0.c0) */
    fp_neg_(&x.c0, &x0.c1);
    x.c1 = x0.c0;
  } else {
    fp2 b, one_alpha;
    one_alpha = alpha;
    fp_add_(&one_alpha.c0, &alpha.c0, &FP_ONE_M);
    f2_pow_(&b, &one_alpha, &FP_P_HALF);
    f2_mul_(&x, &b, &x0);
  }
  fp2 chk;
  f2_sqr_(&chk, &x);
  if (!f2_eq(&chk, a)) return 0;
  *r = x;
  return 1;
}

/* ------------------------------------------------------------------ */
/* expand_message_xmd + hash_to_field                                  */
/* ------------------------------------------------------------------ */

#define H2C_L 64 /* bytes per field element draw */

static int expand_message_xmd(const uint8_t *msg, size_t msg_len,
                              const uint8_t *dst, size_t dst_len,
                              uint8_t *out, size_t len_in_bytes) {
  if (dst_len > 255) return -1;
  size_t ell = (len_in_bytes + 31) / 32;
  if (ell > 255) return -1;
  /* b0 = H(Z_pad || msg || l_i_b || 0x00 || DST') — one-shot buffer */
  uint8_t buf[64 + 4096 + 2 + 1 + 256];
  if (msg_len > 4096) {
    /* messages here are 32-byte roots; cap keeps the buffer static */
    return -1;
  }
  size_t off = 0;
  memset(buf, 0, 64);
  off = 64;
  memcpy(buf + off, msg, msg_len);
  off += msg_len;
  buf[off++] = (uint8_t)(len_in_bytes >> 8);
  buf[off++] = (uint8_t)(len_in_bytes & 0xFF);
  buf[off++] = 0;
  memcpy(buf + off, dst, dst_len);
  off += dst_len;
  buf[off++] = (uint8_t)dst_len;
  uint8_t b0[32];
  ls_sha256(buf, off, b0);

  uint8_t bi[32];
  uint8_t block[32 + 1 + 256];
  /* b1 = H(b0 || 0x01 || DST') */
  memcpy(block, b0, 32);
  block[32] = 1;
  memcpy(block + 33, dst, dst_len);
  block[33 + dst_len] = (uint8_t)dst_len;
  ls_sha256(block, 34 + dst_len, bi);
  size_t copied = 0;
  for (size_t i = 1;; i++) {
    size_t take = len_in_bytes - copied < 32 ? len_in_bytes - copied : 32;
    memcpy(out + copied, bi, take);
    copied += take;
    if (copied >= len_in_bytes) break;
    for (int j = 0; j < 32; j++) block[j] = b0[j] ^ bi[j];
    block[32] = (uint8_t)(i + 1);
    memcpy(block + 33, dst, dst_len);
    block[33 + dst_len] = (uint8_t)dst_len;
    ls_sha256(block, 34 + dst_len, bi);
  }
  return 0;
}

/* 64 big-endian bytes -> Fp element (Montgomery form), via Horner over
 * 64-bit words: r = ((...((w0)*2^64 + w1)*2^64 ...) + w7) mod p. */
static void fp_from_be64bytes(fp *r, const uint8_t *b) {
  fp acc = FP_ZERO; /* 0 in Montgomery form is 0 */
  for (int w = 0; w < 8; w++) {
    uint64_t word = 0;
    for (int k = 0; k < 8; k++) word = (word << 8) | b[w * 8 + k];
    fp_mul_(&acc, &acc, &FP_T64_M); /* acc *= 2^64 (stays in mont) */
    fp wl = {{word, 0, 0, 0, 0, 0}};
    fp wm;
    fp_mul_(&wm, &wl, &FP_R2); /* to_mont(word) */
    fp_add_(&acc, &acc, &wm);
  }
  *r = acc;
}

/* ------------------------------------------------------------------ */
/* SSWU map to E'' and 3-isogeny to E'                                 */
/* ------------------------------------------------------------------ */

static void map_to_curve_sswu(fp2 *x, fp2 *y, const fp2 *t) {
  fp2 t2, zt2, tv1, x1, gx1;
  f2_sqr_(&t2, t);
  f2_mul_(&zt2, &SSWU_Z, &t2); /* Z t^2 */
  f2_sqr_(&tv1, &zt2);
  f2_add_(&tv1, &tv1, &zt2); /* Z^2 t^4 + Z t^2 */
  if (f2_is_zero(&tv1)) {
    x1 = SSWU_B_DIV_ZA;
  } else {
    fp2 inv;
    f2_inv_(&inv, &tv1);
    fp_add_(&inv.c0, &inv.c0, &FP_ONE_M); /* 1 + 1/tv1 */
    f2_mul_(&x1, &SSWU_NEG_B_DIV_A, &inv);
  }
  /* gx1 = x1^3 + A x1 + B */
  fp2 xx, g;
  f2_sqr_(&xx, &x1);
  f2_add_(&xx, &xx, &SSWU_A);
  f2_mul_(&g, &xx, &x1);
  f2_add_(&gx1, &g, &SSWU_B);
  fp2 yy;
  if (f2_sqrt_(&yy, &gx1)) {
    *x = x1;
  } else {
    fp2 x2, gx2;
    f2_mul_(&x2, &zt2, &x1);
    f2_sqr_(&xx, &x2);
    f2_add_(&xx, &xx, &SSWU_A);
    f2_mul_(&g, &xx, &x2);
    f2_add_(&gx2, &g, &SSWU_B);
    f2_sqrt_(&yy, &gx2); /* must succeed: gx1*gx2 is a square */
    *x = x2;
  }
  if (f2_sgn0(t) != f2_sgn0(&yy)) f2_neg_(&yy, &yy);
  *y = yy;
}

static void horner(fp2 *r, const fp2 *coeffs, int n, const fp2 *x) {
  fp2 acc = coeffs[n - 1];
  for (int i = n - 2; i >= 0; i--) {
    f2_mul_(&acc, &acc, x);
    f2_add_(&acc, &acc, &coeffs[i]);
  }
  *r = acc;
}

/* 3-isogeny E'' -> E' with ONE shared inversion for both denominators */
static void iso_map_g2(fp2 *xo, fp2 *yo, const fp2 *x, const fp2 *y) {
  fp2 xn, xd, yn, yd;
  horner(&xn, ISO_XNUM, 4, x);
  horner(&xd, ISO_XDEN, 3, x);
  horner(&yn, ISO_YNUM, 4, x);
  horner(&yd, ISO_YDEN, 4, x);
  fp2 prod, pinv, xdi, ydi;
  f2_mul_(&prod, &xd, &yd);
  f2_inv_(&pinv, &prod);
  f2_mul_(&xdi, &pinv, &yd); /* 1/xd */
  f2_mul_(&ydi, &pinv, &xd); /* 1/yd */
  f2_mul_(xo, &xn, &xdi);
  fp2 t;
  f2_mul_(&t, y, &yn);
  f2_mul_(yo, &t, &ydi);
}

/* ------------------------------------------------------------------ */
/* Jacobian G2 arithmetic (mirrors oracle _CurveOps formulas)          */
/* ------------------------------------------------------------------ */

typedef struct { fp2 X, Y, Z; } jac2;

static void jac2_set_inf(jac2 *r) {
  r->X.c0 = FP_ONE_M; r->X.c1 = FP_ZERO;
  r->Y.c0 = FP_ONE_M; r->Y.c1 = FP_ZERO;
  r->Z.c0 = FP_ZERO;  r->Z.c1 = FP_ZERO;
}

static int jac2_is_inf(const jac2 *p) { return f2_is_zero(&p->Z); }

static void jac2_double(jac2 *r, const jac2 *p) {
  if (jac2_is_inf(p) || f2_is_zero(&p->Y)) { jac2_set_inf(r); return; }
  fp2 A, B, C, D, E, F, t, X3, Y3, Z3;
  f2_sqr_(&A, &p->X);
  f2_sqr_(&B, &p->Y);
  f2_sqr_(&C, &B);
  f2_add_(&t, &p->X, &B);
  f2_sqr_(&t, &t);
  fp2 AC;
  f2_add_(&AC, &A, &C);
  f2_sub_(&D, &t, &AC);
  f2_add_(&D, &D, &D);
  f2_add_(&E, &A, &A);
  f2_add_(&E, &E, &A);
  f2_sqr_(&F, &E);
  fp2 D2;
  f2_add_(&D2, &D, &D);
  f2_sub_(&X3, &F, &D2);
  fp2 C8;
  f2_add_(&C8, &C, &C);
  f2_add_(&C8, &C8, &C8);
  f2_add_(&C8, &C8, &C8);
  f2_sub_(&t, &D, &X3);
  f2_mul_(&Y3, &E, &t);
  f2_sub_(&Y3, &Y3, &C8);
  f2_add_(&t, &p->Y, &p->Y);
  f2_mul_(&Z3, &t, &p->Z);
  r->X = X3; r->Y = Y3; r->Z = Z3;
}

static void jac2_add(jac2 *r, const jac2 *p1, const jac2 *p2) {
  if (jac2_is_inf(p1)) { *r = *p2; return; }
  if (jac2_is_inf(p2)) { *r = *p1; return; }
  fp2 Z1Z1, Z2Z2, U1, U2, S1, S2, t;
  f2_sqr_(&Z1Z1, &p1->Z);
  f2_sqr_(&Z2Z2, &p2->Z);
  f2_mul_(&U1, &p1->X, &Z2Z2);
  f2_mul_(&U2, &p2->X, &Z1Z1);
  f2_mul_(&t, &p1->Y, &p2->Z);
  f2_mul_(&S1, &t, &Z2Z2);
  f2_mul_(&t, &p2->Y, &p1->Z);
  f2_mul_(&S2, &t, &Z1Z1);
  if (f2_eq(&U1, &U2)) {
    if (!f2_eq(&S1, &S2)) { jac2_set_inf(r); return; }
    jac2_double(r, p1);
    return;
  }
  fp2 H, I, J, rr, V, X3, Y3, Z3;
  f2_sub_(&H, &U2, &U1);
  f2_add_(&t, &H, &H);
  f2_sqr_(&I, &t);
  f2_mul_(&J, &H, &I);
  f2_sub_(&rr, &S2, &S1);
  f2_add_(&rr, &rr, &rr);
  f2_mul_(&V, &U1, &I);
  f2_sqr_(&t, &rr);
  f2_sub_(&t, &t, &J);
  fp2 V2;
  f2_add_(&V2, &V, &V);
  f2_sub_(&X3, &t, &V2);
  fp2 S1J;
  f2_mul_(&S1J, &S1, &J);
  f2_sub_(&t, &V, &X3);
  f2_mul_(&Y3, &rr, &t);
  f2_add_(&S1J, &S1J, &S1J);
  f2_sub_(&Y3, &Y3, &S1J);
  f2_add_(&t, &p1->Z, &p2->Z);
  f2_sqr_(&t, &t);
  fp2 ZZ;
  f2_add_(&ZZ, &Z1Z1, &Z2Z2);
  f2_sub_(&t, &t, &ZZ);
  f2_mul_(&Z3, &t, &H);
  r->X = X3; r->Y = Y3; r->Z = Z3;
}

static void jac2_neg(jac2 *r, const jac2 *p) {
  r->X = p->X;
  f2_neg_(&r->Y, &p->Y);
  r->Z = p->Z;
}

/* [k]P for a 64-bit scalar, MSB-first double-and-add */
static void jac2_mul_u64(jac2 *r, const jac2 *p, uint64_t k) {
  jac2 acc;
  jac2_set_inf(&acc);
  for (int i = 63; i >= 0; i--) {
    jac2_double(&acc, &acc);
    if ((k >> i) & 1) jac2_add(&acc, &acc, p);
  }
  *r = acc;
}

/* psi on Jacobian coords without inversion:
 * (X, Y, Z) -> (cx * conj(X), cy * conj(Y), conj(Z))
 * since x = X/Z^2 maps to cx*conj(x) = cx*conj(X)/conj(Z)^2, etc. */
static void jac2_psi(jac2 *r, const jac2 *p) {
  fp2 cx, cy, cz;
  f2_conj_(&cx, &p->X);
  f2_conj_(&cy, &p->Y);
  f2_conj_(&cz, &p->Z);
  f2_mul_(&r->X, &PSI_CX_C, &cx);
  f2_mul_(&r->Y, &PSI_CY_C, &cy);
  r->Z = cz;
}

/* Budroni-Pintore: h_eff*P = [x^2-x-1]P + [x-1]psi(P) + psi^2([2]P),
 * with x negative (|x| = BLS_ABS_X): [x]P = -[|x|]P. */
static void clear_cofactor_g2(jac2 *r, const jac2 *p) {
  jac2 t, x_p, u, x2_p;
  jac2_mul_u64(&t, p, BLS_ABS_X);
  jac2_neg(&x_p, &t); /* [x]P */
  jac2_mul_u64(&u, &x_p, BLS_ABS_X);
  jac2_neg(&x2_p, &u); /* [x^2]P */
  jac2 part1, np, nxp;
  jac2_neg(&nxp, &x_p);
  jac2_add(&part1, &x2_p, &nxp);
  jac2_neg(&np, p);
  jac2_add(&part1, &part1, &np); /* [x^2 - x - 1]P */
  /* [x-1]psi(P) = -[|x|+1]psi(P) = -([|x|]psi(P) + psi(P)) */
  jac2 psip, xpsi, part2;
  jac2_psi(&psip, p);
  jac2_mul_u64(&xpsi, &psip, BLS_ABS_X);
  jac2_add(&xpsi, &xpsi, &psip);
  jac2_neg(&part2, &xpsi);
  /* psi^2([2]P) */
  jac2 twop, part3;
  jac2_double(&twop, p);
  jac2_psi(&part3, &twop);
  jac2_psi(&part3, &part3);
  jac2 s;
  jac2_add(&s, &part1, &part2);
  jac2_add(r, &s, &part3);
}

/* ------------------------------------------------------------------ */
/* public entry                                                        */
/* ------------------------------------------------------------------ */

static void fp_to_be48(uint8_t out[48], const fp *a_mont) {
  fp plain;
  fp_from_mont(&plain, a_mont);
  for (int i = 0; i < 6; i++) {
    uint64_t w = plain.v[5 - i];
    for (int k = 0; k < 8; k++) out[i * 8 + k] = (uint8_t)(w >> (56 - 8 * k));
  }
}

/* Idempotent constant setup, exported so the Python binder can run it
 * once at load time — the lazy h2c_init below is NOT thread-safe on its
 * own (ctypes releases the GIL during foreign calls). */
LS_EXPORT void ls_h2c_warmup(void) { h2c_init(); }

/* out layout: x.c0 || x.c1 || y.c0 || y.c1, 48B big-endian each.
 * Returns 0 on success, negative on failure (oversized inputs / the
 * impossible infinity result). */
LS_EXPORT int ls_hash_to_g2(const uint8_t *msg, size_t msg_len,
                            const uint8_t *dst, size_t dst_len,
                            uint8_t out[192]) {
  h2c_init();
  uint8_t uniform[4 * H2C_L];
  if (expand_message_xmd(msg, msg_len, dst, dst_len, uniform, 4 * H2C_L))
    return -1;
  fp2 u0, u1;
  fp_from_be64bytes(&u0.c0, uniform);
  fp_from_be64bytes(&u0.c1, uniform + H2C_L);
  fp_from_be64bytes(&u1.c0, uniform + 2 * H2C_L);
  fp_from_be64bytes(&u1.c1, uniform + 3 * H2C_L);

  fp2 x0, y0, x1, y1;
  map_to_curve_sswu(&x0, &y0, &u0);
  iso_map_g2(&x0, &y0, &x0, &y0);
  map_to_curve_sswu(&x1, &y1, &u1);
  iso_map_g2(&x1, &y1, &x1, &y1);

  jac2 q0, q1, s, cleared;
  q0.X = x0; q0.Y = y0; q0.Z.c0 = FP_ONE_M; q0.Z.c1 = FP_ZERO;
  q1.X = x1; q1.Y = y1; q1.Z.c0 = FP_ONE_M; q1.Z.c1 = FP_ZERO;
  jac2_add(&s, &q0, &q1);
  clear_cofactor_g2(&cleared, &s);
  if (jac2_is_inf(&cleared)) return -2;

  /* to affine: one Fp2 inversion */
  fp2 zinv, zinv2, zinv3, xa, ya;
  f2_inv_(&zinv, &cleared.Z);
  f2_sqr_(&zinv2, &zinv);
  f2_mul_(&zinv3, &zinv2, &zinv);
  f2_mul_(&xa, &cleared.X, &zinv2);
  f2_mul_(&ya, &cleared.Y, &zinv3);
  fp_to_be48(out, &xa.c0);
  fp_to_be48(out + 48, &xa.c1);
  fp_to_be48(out + 96, &ya.c0);
  fp_to_be48(out + 144, &ya.c1);
  return 0;
}
