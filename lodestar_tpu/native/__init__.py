"""Native runtime kernels: build-on-first-import C library + ctypes bindings.

The reference ships its host hot loops as native/WASM deps (SURVEY §2.3:
@chainsafe/as-sha256 for merkleization, xxhash-wasm for gossip message
ids, snappy for wire compression).  Here they are one dependency-free C
translation unit (csrc/lodestar_native.c) compiled to a shared library
with the system compiler the first time it's needed and bound via ctypes
(the environment has no pybind11; ctypes keeps the binding zero-build).

Every consumer keeps a pure-Python fallback: `available()` gates use, and
LODESTAR_TPU_NO_NATIVE=1 disables the native path entirely (useful for
differential tests of the fallbacks).
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import sys
import threading
from typing import Optional

_HERE = os.path.dirname(os.path.abspath(__file__))
_SRCS = [
    os.path.join(_HERE, "csrc", "lodestar_native.c"),
    os.path.join(_HERE, "csrc", "bls_h2c.c"),
]
_SRC_DEPS = _SRCS + [os.path.join(_HERE, "csrc", "bls_h2c_constants.h")]
_LIB_PATH = os.path.join(_HERE, f"_lodestar_native_{sys.platform}.so")

_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False


def _build() -> bool:
    cc = os.environ.get("CC", "cc")
    cmd = [cc, "-O3", "-shared", "-fPIC", "-fvisibility=hidden",
           "-o", _LIB_PATH, *_SRCS]
    try:
        proc = subprocess.run(cmd, capture_output=True, timeout=120)
    except (OSError, subprocess.TimeoutExpired):
        return False
    return proc.returncode == 0 and os.path.exists(_LIB_PATH)


def _bind(lib: ctypes.CDLL) -> ctypes.CDLL:
    u8p = ctypes.POINTER(ctypes.c_uint8)
    lib.ls_sha256.argtypes = [ctypes.c_char_p, ctypes.c_size_t, u8p]
    lib.ls_sha256.restype = None
    lib.ls_hash_pairs.argtypes = [ctypes.c_char_p, u8p, ctypes.c_size_t]
    lib.ls_hash_pairs.restype = None
    lib.ls_hash_layer.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                  ctypes.c_char_p, u8p]
    lib.ls_hash_layer.restype = None
    lib.ls_xxh64.argtypes = [ctypes.c_char_p, ctypes.c_size_t, ctypes.c_uint64]
    lib.ls_xxh64.restype = ctypes.c_uint64
    lib.ls_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.ls_crc32c.restype = ctypes.c_uint32
    lib.ls_snappy_max_compressed.argtypes = [ctypes.c_size_t]
    lib.ls_snappy_max_compressed.restype = ctypes.c_size_t
    lib.ls_snappy_compress.argtypes = [ctypes.c_char_p, ctypes.c_size_t, u8p]
    lib.ls_snappy_compress.restype = ctypes.c_long
    lib.ls_snappy_uncompressed_length.argtypes = [ctypes.c_char_p, ctypes.c_size_t]
    lib.ls_snappy_uncompressed_length.restype = ctypes.c_long
    lib.ls_snappy_uncompress.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                         u8p, ctypes.c_size_t]
    lib.ls_snappy_uncompress.restype = ctypes.c_long
    try:  # absent in pre-h2c builds of the .so (rebuilt on mtime anyway)
        lib.ls_hash_to_g2.argtypes = [ctypes.c_char_p, ctypes.c_size_t,
                                      ctypes.c_char_p, ctypes.c_size_t, u8p]
        lib.ls_hash_to_g2.restype = ctypes.c_int
        lib.ls_h2c_warmup.argtypes = []
        lib.ls_h2c_warmup.restype = None
        lib.ls_h2c_warmup()  # init derived constants once, single-threaded
    except AttributeError:
        pass
    return lib


def _load() -> Optional[ctypes.CDLL]:
    global _lib, _tried
    if _lib is not None:
        return _lib
    if _tried:
        return None
    # Reviewed exception: double-checked one-time init — after the first
    # load every call returns on the lock-free fast path above; the one
    # locked section (which may compile the .so) runs once at startup.
    with _lock:  # lodelint: disable=transitive-blocking
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if os.environ.get("LODESTAR_TPU_NO_NATIVE") == "1":
            return None
        try:
            if not os.path.exists(_LIB_PATH) or any(
                os.path.getmtime(_LIB_PATH) < os.path.getmtime(src)
                for src in _SRC_DEPS
            ):
                if not _build():
                    return None
            _lib = _bind(ctypes.CDLL(_LIB_PATH))
        except OSError:
            _lib = None
    return _lib


def available() -> bool:
    return _load() is not None


# ---------------------------------------------------------------------------
# typed wrappers
# ---------------------------------------------------------------------------


def sha256(data: bytes) -> bytes:
    lib = _load()
    out = (ctypes.c_uint8 * 32)()
    lib.ls_sha256(data, len(data), out)
    return bytes(out)


def hash_pairs(data: bytes) -> bytes:
    """n*64 bytes of concatenated node pairs -> n*32 bytes of parents."""
    n = len(data) // 64
    out = (ctypes.c_uint8 * (32 * n))()
    lib = _load()
    lib.ls_hash_pairs(data, out, n)
    return bytes(out)


def hash_layer(nodes: bytes, zero: bytes) -> bytes:
    """A merkle layer of len(nodes)/32 nodes -> ceil(n/2) parent nodes;
    an odd tail is paired with `zero`."""
    n = len(nodes) // 32
    out_n = (n + 1) // 2
    out = (ctypes.c_uint8 * (32 * out_n))()
    lib = _load()
    lib.ls_hash_layer(nodes, n, zero, out)
    return bytes(out)


def xxh64(data: bytes, seed: int = 0) -> int:
    return int(_load().ls_xxh64(data, len(data), seed))


def crc32c(data: bytes) -> int:
    return int(_load().ls_crc32c(data, len(data)))


def snappy_compress(data: bytes) -> bytes:
    lib = _load()
    cap = lib.ls_snappy_max_compressed(len(data))
    out = (ctypes.c_uint8 * cap)()
    n = lib.ls_snappy_compress(data, len(data), out)
    if n < 0:
        raise ValueError("snappy compression failed")
    return bytes(out[:n])


def has_h2c() -> bool:
    lib = _load()
    return lib is not None and hasattr(lib, "ls_hash_to_g2")


def hash_to_g2_affine(msg: bytes, dst: bytes):
    """Native RFC-9380 hash_to_curve for G2; returns the oracle's affine
    format ((x0, x1), (y0, y1)) of python ints.  ~100x the pure-Python
    oracle's speed (the role blst's in-C h2c plays for the reference)."""
    lib = _load()
    out = (ctypes.c_uint8 * 192)()
    rc = lib.ls_hash_to_g2(msg, len(msg), dst, len(dst), out)
    if rc != 0:
        raise ValueError(f"ls_hash_to_g2 failed rc={rc}")
    b = bytes(out)
    x0, x1, y0, y1 = (
        int.from_bytes(b[i * 48 : (i + 1) * 48], "big") for i in range(4)
    )
    return ((x0, x1), (y0, y1))


def snappy_uncompress(data: bytes, max_len: int = 1 << 27) -> bytes:
    lib = _load()
    n = lib.ls_snappy_uncompressed_length(data, len(data))
    if n < 0 or n > max_len:
        raise ValueError("invalid snappy length")
    out = (ctypes.c_uint8 * n)() if n else (ctypes.c_uint8 * 1)()
    got = lib.ls_snappy_uncompress(data, len(data), out, n)
    if got != n:
        raise ValueError("corrupt snappy data")
    return bytes(out[:n])
