from .schema import Bucket, encode_key  # noqa: F401
from .controller import KvController, MemoryController, SqliteController  # noqa: F401
from .repository import Repository  # noqa: F401
from .beacon import BeaconDb  # noqa: F401
