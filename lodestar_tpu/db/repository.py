"""Typed repository base over a KV bucket (reference:
packages/db/src/abstractRepository.ts + beacon-node/src/db/repositories/).
"""
from __future__ import annotations

from typing import Generic, Iterator, List, Optional, Tuple, TypeVar

from .controller import KvController
from .schema import Bucket, encode_key

T = TypeVar("T")


class Repository(Generic[T]):
    """Bucketed, SSZ-encoded collection keyed by bytes (roots) or ints
    (slots/indices, big-endian for ordered scans)."""

    def __init__(self, db: KvController, bucket: Bucket, ssz_type, key_length: int = 8):
        self.db = db
        self.bucket = bucket
        self.type = ssz_type
        self.key_length = key_length

    # key helpers ------------------------------------------------------

    def _k(self, key) -> bytes:
        if isinstance(key, int):
            key = key.to_bytes(self.key_length, "big")
        return encode_key(self.bucket, key)

    def _decode_id(self, dbkey: bytes):
        raw = dbkey[1:]
        return raw

    # value helpers (subclasses may override for non-SSZ values) --------

    def encode_value(self, value: T) -> bytes:
        return self.type.serialize(value)

    def decode_value(self, data: bytes) -> T:
        return self.type.deserialize(data)

    # crud -------------------------------------------------------------

    def get(self, key) -> Optional[T]:
        data = self.db.get(self._k(key))
        return self.decode_value(data) if data is not None else None

    def get_binary(self, key) -> Optional[bytes]:
        return self.db.get(self._k(key))

    def has(self, key) -> bool:
        return self.db.get(self._k(key)) is not None

    def put(self, key, value: T) -> None:
        self.db.put(self._k(key), self.encode_value(value))

    def put_binary(self, key, data: bytes) -> None:
        self.db.put(self._k(key), data)

    def delete(self, key) -> None:
        self.db.delete(self._k(key))

    def batch_put(self, items: List[Tuple[object, T]]) -> None:
        self.db.batch_put((self._k(k), self.encode_value(v)) for k, v in items)

    # range scans ------------------------------------------------------

    def _bounds(self, gte=None, lt=None) -> Tuple[bytes, bytes]:
        lo = self._k(gte) if gte is not None else encode_key(self.bucket, b"")
        hi = (
            self._k(lt)
            if lt is not None
            else bytes([int(self.bucket) + 1])
        )
        return lo, hi

    def keys(self, gte=None, lt=None, reverse=False, limit=None) -> Iterator[bytes]:
        lo, hi = self._bounds(gte, lt)
        for k in self.db.keys_range(lo, hi, reverse, limit):
            yield self._decode_id(k)

    def values(self, gte=None, lt=None, reverse=False, limit=None) -> Iterator[T]:
        lo, hi = self._bounds(gte, lt)
        for _, v in self.db.entries_range(lo, hi, reverse, limit):
            yield self.decode_value(v)

    def entries(self, gte=None, lt=None, reverse=False, limit=None):
        lo, hi = self._bounds(gte, lt)
        for k, v in self.db.entries_range(lo, hi, reverse, limit):
            yield self._decode_id(k), self.decode_value(v)

    def first_value(self) -> Optional[T]:
        for v in self.values(limit=1):
            return v
        return None

    def last_value(self) -> Optional[T]:
        for v in self.values(reverse=True, limit=1):
            return v
        return None
