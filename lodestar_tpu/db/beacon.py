"""BeaconDb: all typed repositories (reference:
packages/beacon-node/src/db/beacon.ts + repositories/).
"""
from __future__ import annotations

from lodestar_tpu.types import ssz
from lodestar_tpu.ssz.core import Bytes32, uint64
from .controller import KvController, MemoryController
from .repository import Repository
from .schema import Bucket


class _RootRepo(Repository):
    """Values keyed by their hash tree root (e.g. hot blocks)."""

    def __init__(self, db, bucket, ssz_type, root_of):
        super().__init__(db, bucket, ssz_type, key_length=32)
        self._root_of = root_of

    def add(self, value) -> bytes:
        root = self._root_of(value)
        self.put(root, value)
        return root


class BeaconDb:
    def __init__(self, controller: KvController = None):
        db = controller if controller is not None else MemoryController()
        self.controller = db
        # hot blocks by root
        self.block = _RootRepo(
            db,
            Bucket.allForks_block,
            ssz.phase0.SignedBeaconBlock,
            lambda sb: ssz.phase0.BeaconBlock.hash_tree_root(sb.message),
        )
        # finalized chain by slot
        self.block_archive = Repository(
            db, Bucket.allForks_blockArchive, ssz.phase0.SignedBeaconBlock
        )
        self.block_archive_root_index = Repository(
            db, Bucket.index_blockArchiveRootIndex, uint64, key_length=32
        )
        self.state_archive = Repository(
            db, Bucket.allForks_stateArchive, ssz.phase0.BeaconState
        )
        self.state_archive_root_index = Repository(
            db, Bucket.index_stateArchiveRootIndex, uint64, key_length=32
        )
        self.deposit_event = Repository(
            db, Bucket.phase0_depositEvent, ssz.phase0.DepositEvent
        )
        self.deposit_data_root = Repository(
            db, Bucket.index_depositDataRoot, Bytes32
        )
        self.eth1_data = Repository(
            db, Bucket.phase0_eth1Data, ssz.phase0.Eth1Data
        )
        self.voluntary_exit = Repository(
            db, Bucket.phase0_exit, ssz.phase0.SignedVoluntaryExit
        )
        self.proposer_slashing = Repository(
            db, Bucket.phase0_proposerSlashing, ssz.phase0.ProposerSlashing
        )
        self.attester_slashing = Repository(
            db, Bucket.phase0_attesterSlashing, ssz.phase0.AttesterSlashing, key_length=32
        )
        self.best_light_client_update = Repository(
            db, Bucket.lightClient_bestLightClientUpdate, ssz.altair.LightClientUpdate
        )
        self.checkpoint_header = Repository(
            db, Bucket.lightClient_checkpointHeader, ssz.phase0.BeaconBlockHeader, key_length=32
        )
        self.backfilled_ranges = Repository(
            db, Bucket.backfilled_ranges, uint64
        )

    def close(self) -> None:
        self.controller.close()
