"""BeaconDb: all typed repositories (reference:
packages/beacon-node/src/db/beacon.ts + repositories/).
"""
from __future__ import annotations

from lodestar_tpu.params import FORK_ORDER, FORK_SEQ, ForkName
from lodestar_tpu.types import ssz, types_for
from lodestar_tpu.ssz.core import Bytes32, uint64
from .controller import KvController, MemoryController
from .repository import Repository
from .schema import Bucket


class MultiForkType:
    """Fork-tagged SSZ codec: one leading byte selects the per-fork
    container (the reference resolves fork types by slot via
    config.getForkTypes; a tag byte keeps the repo self-describing)."""

    def __init__(self, types_by_fork):
        self._by_fork = dict(types_by_fork)
        self._by_tag = {FORK_SEQ[f]: t for f, t in self._by_fork.items()}
        self._tag_of_type = {t: FORK_SEQ[f] for f, t in self._by_fork.items()}

    def serialize(self, value) -> bytes:
        t = type(value)
        tag = self._tag_of_type.get(t)
        if tag is None:
            raise TypeError(f"no fork codec for {t!r}")
        return bytes([tag]) + t.serialize(value)

    def deserialize(self, data: bytes):
        if not data:
            raise ValueError("empty multi-fork value")
        t = self._by_tag.get(data[0])
        if t is None:
            raise ValueError(f"unknown fork tag {data[0]}")
        return t.deserialize(data[1:])


_SIGNED_BLOCK_MF = MultiForkType({f: types_for(f)[2] for f in FORK_ORDER})
_STATE_MF = MultiForkType({f: types_for(f)[0] for f in FORK_ORDER})


class _RootRepo(Repository):
    """Values keyed by their hash tree root (e.g. hot blocks)."""

    def __init__(self, db, bucket, ssz_type, root_of):
        super().__init__(db, bucket, ssz_type, key_length=32)
        self._root_of = root_of

    def add(self, value) -> bytes:
        root = self._root_of(value)
        self.put(root, value)
        return root


class BeaconDb:
    def __init__(self, controller: KvController = None):
        db = controller if controller is not None else MemoryController()
        self.controller = db
        # hot blocks by root
        self.block = _RootRepo(
            db,
            Bucket.allForks_block,
            _SIGNED_BLOCK_MF,
            lambda sb: type(sb.message).hash_tree_root(sb.message),
        )
        # finalized chain by slot
        self.block_archive = Repository(
            db, Bucket.allForks_blockArchive, _SIGNED_BLOCK_MF
        )
        self.block_archive_root_index = Repository(
            db, Bucket.index_blockArchiveRootIndex, uint64, key_length=32
        )
        self.state_archive = Repository(
            db, Bucket.allForks_stateArchive, _STATE_MF
        )
        self.state_archive_root_index = Repository(
            db, Bucket.index_stateArchiveRootIndex, uint64, key_length=32
        )
        self.deposit_event = Repository(
            db, Bucket.phase0_depositEvent, ssz.phase0.DepositEvent
        )
        self.deposit_data_root = Repository(
            db, Bucket.index_depositDataRoot, Bytes32
        )
        self.eth1_data = Repository(
            db, Bucket.phase0_eth1Data, ssz.phase0.Eth1Data
        )
        self.voluntary_exit = Repository(
            db, Bucket.phase0_exit, ssz.phase0.SignedVoluntaryExit
        )
        self.proposer_slashing = Repository(
            db, Bucket.phase0_proposerSlashing, ssz.phase0.ProposerSlashing
        )
        self.attester_slashing = Repository(
            db, Bucket.phase0_attesterSlashing, ssz.phase0.AttesterSlashing, key_length=32
        )
        self.best_light_client_update = Repository(
            db, Bucket.lightClient_bestLightClientUpdate, ssz.altair.LightClientUpdate
        )
        self.checkpoint_header = Repository(
            db, Bucket.lightClient_checkpointHeader, ssz.phase0.BeaconBlockHeader, key_length=32
        )
        self.backfilled_ranges = Repository(
            db, Bucket.backfilled_ranges, uint64
        )
        # eip4844 blobs sidecars (repositories/blobsSidecar.ts): hot by
        # block root, archived by slot after finalization
        self.blobs_sidecar = _RootRepo(
            db,
            Bucket.allForks_blobsSidecar,
            ssz.eip4844.BlobsSidecar,
            lambda sc: bytes(sc.beacon_block_root),
        )
        self.blobs_sidecar_archive = Repository(
            db, Bucket.allForks_blobsSidecarArchive, ssz.eip4844.BlobsSidecar
        )

    def close(self) -> None:
        self.controller.close()
