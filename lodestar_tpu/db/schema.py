"""Database buckets and key encoding (reference: packages/db/src/schema.ts).

Bucket ids match the reference's live (non-deprecated) assignments so a
database layout diagram from the reference maps 1:1.
"""
from __future__ import annotations

from enum import IntEnum


class Bucket(IntEnum):
    allForks_stateArchive = 0           # Root -> BeaconState
    allForks_block = 1                  # Root -> SignedBeaconBlock
    allForks_blockArchive = 2           # Slot -> SignedBeaconBlock
    index_blockArchiveParentRootIndex = 3
    index_blockArchiveRootIndex = 4
    index_mainChain = 6                 # Slot -> Root
    index_chainInfo = 7
    phase0_eth1Data = 8                 # timestamp -> Eth1Data
    index_depositDataRoot = 9           # depositIndex -> Root
    phase0_depositEvent = 19            # depositIndex -> DepositEvent
    phase0_preGenesisState = 30
    phase0_preGenesisStateLastProcessedBlock = 31
    phase0_exit = 13                    # ValidatorIndex -> SignedVoluntaryExit
    phase0_proposerSlashing = 14
    phase0_attesterSlashing = 15
    phase0_slashingProtectionBlockBySlot = 20
    phase0_slashingProtectionAttestationByTarget = 21
    phase0_slashingProtectionAttestationLowerBound = 22
    index_slashingProtectionMinSpanDistance = 23
    index_slashingProtectionMaxSpanDistance = 24
    index_stateArchiveRootIndex = 26    # StateRoot -> Slot
    lightClient_syncCommitteeWitness = 51
    lightClient_syncCommittee = 52
    lightClient_checkpointHeader = 53
    lightClient_bestLightClientUpdate = 55
    validator_metaData = 41
    backfilled_ranges = 42
    allForks_blobsSidecar = 60          # Root -> BlobsSidecar (hot)
    allForks_blobsSidecarArchive = 61   # Slot -> BlobsSidecar (finalized)


def encode_key(bucket: Bucket, key: bytes) -> bytes:
    """bucket-prefixed key (schema.ts:91 uses a 1-byte prefix; ints are
    big-endian so range scans order correctly)."""
    return bytes([int(bucket)]) + key


def uint_key(value: int, length: int = 8) -> bytes:
    return int(value).to_bytes(length, "big")
