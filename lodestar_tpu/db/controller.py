"""Key-value store controllers (reference:
packages/db/src/controller/level.ts backed by C++ leveldown).

The rebuild's durable backend is sqlite3 (stdlib, C storage engine —
filling leveldown's native-code role without an external dependency): one
table of (key BLOB PRIMARY KEY, value BLOB) gives ordered iteration and
range scans like LevelDB.  MemoryController is the test/dev double.
"""
from __future__ import annotations

import sqlite3
import threading
from typing import Iterable, Iterator, List, Optional, Protocol, Tuple


class KvController(Protocol):
    def get(self, key: bytes) -> Optional[bytes]: ...
    def put(self, key: bytes, value: bytes) -> None: ...
    def delete(self, key: bytes) -> None: ...
    def batch_put(self, items: Iterable[Tuple[bytes, bytes]]) -> None: ...
    def keys_range(self, gte: bytes, lt: bytes, reverse: bool = False,
                   limit: Optional[int] = None) -> Iterator[bytes]: ...
    def entries_range(self, gte: bytes, lt: bytes, reverse: bool = False,
                      limit: Optional[int] = None) -> Iterator[Tuple[bytes, bytes]]: ...
    def close(self) -> None: ...


class MemoryController:
    def __init__(self):
        self._data = {}

    def get(self, key):
        return self._data.get(key)

    def put(self, key, value):
        self._data[bytes(key)] = bytes(value)

    def delete(self, key):
        self._data.pop(bytes(key), None)

    def batch_put(self, items):
        for k, v in items:
            self.put(k, v)

    def entries_range(self, gte, lt, reverse=False, limit=None):
        keys = sorted(k for k in self._data if gte <= k < lt)
        if reverse:
            keys.reverse()
        if limit is not None:
            keys = keys[:limit]
        for k in keys:
            yield k, self._data[k]

    def keys_range(self, gte, lt, reverse=False, limit=None):
        for k, _ in self.entries_range(gte, lt, reverse, limit):
            yield k

    def close(self):
        self._data.clear()


# lodelint: disable-file=transitive-blocking
# Reviewed exception (lodelint interprocedural gate): every method below
# takes self._lock, which lodelint's effect analysis reaches from async
# paths (validator signing -> slashing protection -> put).  The lock is
# required for cross-thread safety — executor threads share this
# connection — and is held only for single-row sqlite statements under
# WAL (sub-ms, no network, no compile).  Bulk work against this store
# (keymanager interchange import/export, archival) is dispatched via
# run_in_executor at the call sites, so loop-side acquisitions are
# single-row and effectively uncontended.  Switching to asyncio.Lock
# here would break the executor threads that must also serialize.


class SqliteController:
    """Durable KV store; thread-safe via a lock (the asyncio host runs
    blocking db work in an executor)."""

    def __init__(self, path: str):
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._lock = threading.Lock()
        with self._lock:
            self._conn.execute(
                "CREATE TABLE IF NOT EXISTS kv"
                " (key BLOB PRIMARY KEY, value BLOB NOT NULL) WITHOUT ROWID"
            )
            self._conn.execute("PRAGMA journal_mode=WAL")
            self._conn.execute("PRAGMA synchronous=NORMAL")
            self._conn.commit()

    def get(self, key):
        with self._lock:
            row = self._conn.execute(
                "SELECT value FROM kv WHERE key=?", (bytes(key),)
            ).fetchone()
        return row[0] if row else None

    def put(self, key, value):
        with self._lock:
            self._conn.execute(
                "INSERT OR REPLACE INTO kv (key, value) VALUES (?, ?)",
                (bytes(key), bytes(value)),
            )
            self._conn.commit()

    def delete(self, key):
        with self._lock:
            self._conn.execute("DELETE FROM kv WHERE key=?", (bytes(key),))
            self._conn.commit()

    def batch_put(self, items):
        with self._lock:
            self._conn.executemany(
                "INSERT OR REPLACE INTO kv (key, value) VALUES (?, ?)",
                [(bytes(k), bytes(v)) for k, v in items],
            )
            self._conn.commit()

    def entries_range(self, gte, lt, reverse=False, limit=None):
        order = "DESC" if reverse else "ASC"
        q = f"SELECT key, value FROM kv WHERE key >= ? AND key < ? ORDER BY key {order}"
        if limit is not None:
            q += f" LIMIT {int(limit)}"
        with self._lock:
            rows = self._conn.execute(q, (bytes(gte), bytes(lt))).fetchall()
        for k, v in rows:
            yield bytes(k), bytes(v)

    def keys_range(self, gte, lt, reverse=False, limit=None):
        for k, _ in self.entries_range(gte, lt, reverse, limit):
            yield k

    def close(self):
        with self._lock:
            self._conn.close()
