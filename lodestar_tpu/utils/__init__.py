"""Shared runtime utilities — the rebuild's `@lodestar/utils`
(reference: packages/utils/src: logger/winston.ts, sleep.ts, retry.ts,
bytes.ts hex helpers).

The logger mirrors the reference's winston setup in shape: leveled,
per-module child loggers, one line per record with an ISO timestamp and
the module chain, writing to stderr (and optionally a file) so stdout
stays clean for machine-readable output (the CLI's JSON lines).
"""
from __future__ import annotations

import asyncio
import sys
import time
from enum import IntEnum
from typing import Awaitable, Callable, Optional, TypeVar

T = TypeVar("T")


# ---------------------------------------------------------------------------
# logger (utils/src/logger/winston.ts role)
# ---------------------------------------------------------------------------


class LogLevel(IntEnum):
    error = 0
    warn = 1
    info = 2
    verbose = 3
    debug = 4
    trace = 5


class Logger:
    """Leveled logger with child-module chaining (`logger.child("chain")`
    prints records as `[node chain] ...` like the reference's winston
    childLogger-per-subsystem pattern, node/nodejs.ts:166)."""

    def __init__(
        self,
        module: str = "",
        level: LogLevel = LogLevel.info,
        stream=None,
        file_path: Optional[str] = None,
        _shared=None,
    ):
        self.module = module
        self.level = level
        self._stream = stream if stream is not None else sys.stderr
        # file handle shared between a logger and its children
        self._shared = _shared if _shared is not None else {"file": None}
        if file_path:
            self._shared["file"] = open(file_path, "a")

    def child(self, module: str) -> "Logger":
        name = f"{self.module} {module}".strip()
        return Logger(name, self.level, self._stream, _shared=self._shared)

    def _log(self, level: LogLevel, msg: str, **ctx) -> None:
        if level > self.level:
            return
        ts = time.strftime("%Y-%m-%d %H:%M:%S", time.gmtime())
        ctx_s = " ".join(f"{k}={v}" for k, v in ctx.items())
        mod = f"[{self.module}] " if self.module else ""
        line = f"{ts} {level.name:<7} {mod}{msg}" + (f" {ctx_s}" if ctx_s else "")
        print(line, file=self._stream, flush=True)
        f = self._shared.get("file")
        if f is not None:
            print(line, file=f, flush=True)

    def error(self, msg: str, **ctx) -> None:
        self._log(LogLevel.error, msg, **ctx)

    def warn(self, msg: str, **ctx) -> None:
        self._log(LogLevel.warn, msg, **ctx)

    def info(self, msg: str, **ctx) -> None:
        self._log(LogLevel.info, msg, **ctx)

    def verbose(self, msg: str, **ctx) -> None:
        self._log(LogLevel.verbose, msg, **ctx)

    def debug(self, msg: str, **ctx) -> None:
        self._log(LogLevel.debug, msg, **ctx)


_root = Logger()


def get_logger(module: str = "", level: Optional[LogLevel] = None) -> Logger:
    lg = _root.child(module) if module else _root
    if level is not None:
        lg.level = level
    return lg


# ---------------------------------------------------------------------------
# sleep / retry (utils/src/{sleep,retry}.ts)
# ---------------------------------------------------------------------------


async def sleep(seconds: float) -> None:
    await asyncio.sleep(seconds)


class RetryError(Exception):
    def __init__(self, attempts: int, last: BaseException):
        super().__init__(f"all {attempts} attempts failed: {last!r}")
        self.attempts = attempts
        self.last = last


async def retry(
    fn: Callable[[], Awaitable[T]],
    retries: int = 3,
    retry_delay: float = 0.5,
    should_retry: Optional[Callable[[BaseException], bool]] = None,
) -> T:
    """Run `fn` up to `retries` times with a fixed delay between attempts
    (reference retry.ts semantics: shouldRetry gates each re-attempt)."""
    last: Optional[BaseException] = None
    for attempt in range(retries):
        try:
            return await fn()
        except Exception as e:  # noqa: BLE001 — retry boundary
            last = e
            if should_retry is not None and not should_retry(e):
                raise
            if attempt < retries - 1:
                await asyncio.sleep(retry_delay)
    raise RetryError(retries, last)


async def gather_settled(*aws) -> list:
    """Settle every awaitable, then surface the first failure — a failing
    child can't leave siblings running detached with unretrieved
    exceptions (lodelint gather-exceptions).  Results keep input order."""
    results = await asyncio.gather(*aws, return_exceptions=True)
    for r in results:
        if isinstance(r, BaseException):
            raise r
    return list(results)


# ---------------------------------------------------------------------------
# bytes/hex helpers (utils/src/bytes.ts)
# ---------------------------------------------------------------------------


def to_hex(b: bytes) -> str:
    return "0x" + bytes(b).hex()


def from_hex(s: str) -> bytes:
    return bytes.fromhex(s.removeprefix("0x"))


def bytes_to_int(b: bytes) -> int:
    return int.from_bytes(b, "little")


def int_to_bytes(x: int, length: int) -> bytes:
    return int(x).to_bytes(length, "little")
