"""Snappy codec: raw block format (gossip messages) and framed format
(reqresp streams) — the role the reference fills with C snappy bindings
(@chainsafe/snappy-stream, snappyjs; SURVEY §2.3).

Decompressor implements the full Snappy spec (literals + all three copy
element kinds).  The compressor emits literal-only blocks: always valid
Snappy (the format permits arbitrary literal chunking), trading ratio for
simplicity — wire-compatible with any conformant peer.  The framed format
implements the official framing spec with masked CRC-32C checksums.
"""
from __future__ import annotations

import struct
from typing import Iterator, List, Tuple

from lodestar_tpu import native as _native

_NATIVE = _native.available()

# ---------------------------------------------------------------------------
# varint
# ---------------------------------------------------------------------------


def _write_uvarint(n: int) -> bytes:
    out = bytearray()
    while True:
        b = n & 0x7F
        n >>= 7
        if n:
            out.append(b | 0x80)
        else:
            out.append(b)
            return bytes(out)


def _read_uvarint(data: bytes, pos: int) -> Tuple[int, int]:
    shift = 0
    result = 0
    while True:
        if pos >= len(data):
            raise ValueError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 63:
            raise ValueError("varint too long")


# ---------------------------------------------------------------------------
# raw block format
# ---------------------------------------------------------------------------

_MAX_LITERAL = 60  # tag-encoded literal lengths 1..60


def compress(data: bytes) -> bytes:
    """Snappy block compression.

    Native path (lodestar_tpu/native): real LZ77 matching, the role of the
    reference's C snappy.  Fallback: literal-only blocks (valid per format
    spec §2.1), trading ratio for simplicity."""
    if _NATIVE:
        return _native.snappy_compress(bytes(data))
    return _py_compress(data)


def _py_compress(data: bytes) -> bytes:
    out = bytearray(_write_uvarint(len(data)))
    pos = 0
    n = len(data)
    while pos < n:
        chunk = data[pos : pos + 65536]
        length = len(chunk)
        if length <= _MAX_LITERAL:
            out.append((length - 1) << 2)
        elif length < (1 << 8):
            out.append(60 << 2)
            out.append(length - 1)
        else:
            out.append(61 << 2)
            out += struct.pack("<H", length - 1)
        out += chunk
        pos += length
    return bytes(out)


def decompress(data: bytes) -> bytes:
    if _NATIVE:
        try:
            return _native.snappy_uncompress(bytes(data))
        except ValueError as e:
            raise ValueError(f"corrupt snappy block: {e}") from e
    return _py_decompress(data)


def _py_decompress(data: bytes) -> bytes:
    expected_len, pos = _read_uvarint(data, 0)
    out = bytearray()
    n = len(data)
    while pos < n:
        tag = data[pos]
        pos += 1
        kind = tag & 3
        if kind == 0:  # literal
            length = (tag >> 2) + 1
            if length > 60:
                extra = length - 60
                if pos + extra > n:
                    raise ValueError("truncated literal length")
                length = int.from_bytes(data[pos : pos + extra], "little") + 1
                pos += extra
            if pos + length > n:
                raise ValueError("truncated literal")
            out += data[pos : pos + length]
            pos += length
            continue
        if kind == 1:  # copy, 1-byte offset
            length = ((tag >> 2) & 0x7) + 4
            if pos >= n:
                raise ValueError("truncated copy-1")
            offset = ((tag >> 5) << 8) | data[pos]
            pos += 1
        elif kind == 2:  # copy, 2-byte offset
            length = (tag >> 2) + 1
            if pos + 2 > n:
                raise ValueError("truncated copy-2")
            offset = struct.unpack_from("<H", data, pos)[0]
            pos += 2
        else:  # copy, 4-byte offset
            length = (tag >> 2) + 1
            if pos + 4 > n:
                raise ValueError("truncated copy-4")
            offset = struct.unpack_from("<I", data, pos)[0]
            pos += 4
        if offset == 0 or offset > len(out):
            raise ValueError("bad copy offset")
        # overlapping copies are byte-at-a-time semantics
        start = len(out) - offset
        for i in range(length):
            out.append(out[start + i])
    if len(out) != expected_len:
        raise ValueError(f"length mismatch {len(out)} != {expected_len}")
    return bytes(out)


# ---------------------------------------------------------------------------
# CRC-32C (Castagnoli), masked per the framing spec
# ---------------------------------------------------------------------------

_CRC_TABLE: List[int] = []


def _crc_table() -> List[int]:
    global _CRC_TABLE
    if not _CRC_TABLE:
        poly = 0x82F63B78
        tbl = []
        for i in range(256):
            c = i
            for _ in range(8):
                c = (c >> 1) ^ poly if c & 1 else c >> 1
            tbl.append(c)
        _CRC_TABLE = tbl
    return _CRC_TABLE


def crc32c(data: bytes) -> int:
    if _NATIVE:
        return _native.crc32c(bytes(data))
    tbl = _crc_table()
    crc = 0xFFFFFFFF
    for b in data:
        crc = tbl[(crc ^ b) & 0xFF] ^ (crc >> 8)
    return crc ^ 0xFFFFFFFF


def _masked_crc(data: bytes) -> int:
    """mask(crc) = rotr15(crc) + 0xa282ead8 (framing spec §3)."""
    c = crc32c(data)
    return ((((c >> 15) | (c << 17)) & 0xFFFFFFFF) + 0xA282EAD8) & 0xFFFFFFFF


# ---------------------------------------------------------------------------
# framed format (reqresp streams)
# ---------------------------------------------------------------------------

STREAM_IDENTIFIER = b"\xff\x06\x00\x00sNaPpY"
_CHUNK_COMPRESSED = 0x00
_CHUNK_UNCOMPRESSED = 0x01
_MAX_FRAME_DATA = 65536


def frame_compress(data: bytes) -> bytes:
    """Framed snappy: stream id + one chunk per <=64KiB of input."""
    out = bytearray(STREAM_IDENTIFIER)
    for pos in range(0, len(data), _MAX_FRAME_DATA) or [0]:
        chunk = data[pos : pos + _MAX_FRAME_DATA]
        body = struct.pack("<I", _masked_crc(chunk)) + compress(chunk)
        if len(body) >= len(chunk) + 4:
            body = struct.pack("<I", _masked_crc(chunk)) + chunk
            kind = _CHUNK_UNCOMPRESSED
        else:
            kind = _CHUNK_COMPRESSED
        out += bytes([kind]) + len(body).to_bytes(3, "little") + body
    if not data:
        body = struct.pack("<I", _masked_crc(b"")) + compress(b"")
        out += bytes([_CHUNK_COMPRESSED]) + len(body).to_bytes(3, "little") + body
    return bytes(out)


def frame_decompress(data: bytes) -> bytes:
    pos = 0
    out = bytearray()
    seen_stream_id = False
    while pos < len(data):
        if pos + 4 > len(data):
            raise ValueError("truncated frame header")
        kind = data[pos]
        length = int.from_bytes(data[pos + 1 : pos + 4], "little")
        pos += 4
        if pos + length > len(data):
            raise ValueError("truncated frame body")
        body = data[pos : pos + length]
        pos += length
        if kind == 0xFF:
            if body != STREAM_IDENTIFIER[4:]:
                raise ValueError("bad stream identifier")
            seen_stream_id = True
            continue
        if not seen_stream_id:
            raise ValueError("missing stream identifier")
        if kind == _CHUNK_COMPRESSED:
            crc = struct.unpack("<I", body[:4])[0]
            chunk = decompress(body[4:])
        elif kind == _CHUNK_UNCOMPRESSED:
            crc = struct.unpack("<I", body[:4])[0]
            chunk = body[4:]
        elif 0x80 <= kind <= 0xFD:
            continue  # skippable padding
        else:
            raise ValueError(f"unknown chunk kind {kind:#x}")
        if _masked_crc(chunk) != crc:
            raise ValueError("frame checksum mismatch")
        out += chunk
    return bytes(out)
