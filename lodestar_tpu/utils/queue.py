"""Async job queues with backpressure (reference:
packages/beacon-node/src/util/queue/itemQueue.ts — JobItemQueue with
LIFO/FIFO order, maxLength drop policy, maxConcurrency).
"""
from __future__ import annotations

import asyncio
import collections
import time
from dataclasses import dataclass
from enum import Enum
from typing import Awaitable, Callable, Deque, Generic, Optional, TypeVar

T = TypeVar("T")
R = TypeVar("R")


class QueueType(str, Enum):
    FIFO = "FIFO"
    LIFO = "LIFO"


class QueueError(Exception):
    pass


class QueueFullError(QueueError):
    pass


class QueueAbortedError(QueueError):
    pass


@dataclass
class QueueMetrics:
    length: int = 0
    dropped_jobs: int = 0
    total_jobs: int = 0


class JobItemQueue(Generic[T, R]):
    """Push items; an async processor consumes them with bounded
    concurrency.  When full, the OLDEST pending job is dropped in LIFO
    mode (gossip wants freshest first) or the new job is rejected in FIFO
    mode — matching itemQueue.ts semantics."""

    def __init__(
        self,
        process: Callable[[T], Awaitable[R]],
        max_length: int = 1024,
        queue_type: QueueType = QueueType.FIFO,
        max_concurrency: int = 1,
        name: str = "queue",
    ):
        self._process = process
        self.max_length = max_length
        self.queue_type = queue_type
        self.max_concurrency = max_concurrency
        self.name = name
        self._items: Deque = collections.deque()
        self._running = 0
        self._aborted = False
        self.metrics = QueueMetrics()
        self._tasks: set = set()

    def __len__(self) -> int:
        return len(self._items)

    def push(self, item: T) -> "asyncio.Future[R]":
        if self._aborted:
            raise QueueAbortedError(self.name)
        loop = asyncio.get_running_loop()
        fut = loop.create_future()
        if len(self._items) >= self.max_length:
            if self.queue_type is QueueType.LIFO:
                # drop the oldest pending job to make room
                _, dropped = self._items.popleft()
                if not dropped.done():
                    dropped.set_exception(QueueFullError(self.name))
                self.metrics.dropped_jobs += 1
            else:
                self.metrics.dropped_jobs += 1
                fut.set_exception(QueueFullError(self.name))
                return fut
        self._items.append((item, fut))
        self.metrics.length = len(self._items)
        self._pump()
        return fut

    def _pump(self) -> None:
        while self._running < self.max_concurrency and self._items:
            if self.queue_type is QueueType.LIFO:
                item, fut = self._items.pop()
            else:
                item, fut = self._items.popleft()
            self.metrics.length = len(self._items)
            self._running += 1
            task = asyncio.ensure_future(self._run(item, fut))
            self._tasks.add(task)
            task.add_done_callback(self._tasks.discard)

    async def _run(self, item: T, fut: "asyncio.Future[R]") -> None:
        try:
            result = await self._process(item)
            if not fut.done():
                fut.set_result(result)
        except asyncio.CancelledError:
            # abort() cancelled us: the caller awaiting the future must
            # see the queue-level error, not a bare cancellation
            if not fut.done():
                fut.set_exception(QueueAbortedError(self.name))
            raise
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
        finally:
            self.metrics.total_jobs += 1
            self._running -= 1
            if not self._aborted:
                self._pump()

    def abort(self) -> None:
        self._aborted = True
        while self._items:
            _, fut = self._items.popleft()
            if not fut.done():
                fut.set_exception(QueueAbortedError(self.name))
        # in-flight jobs: cancel rather than strand them running against
        # an aborted queue (their futures resolve in _run's handler)
        for task in tuple(self._tasks):
            task.cancel()
        self.metrics.length = 0
